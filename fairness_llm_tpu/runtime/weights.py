"""HF safetensors checkpoints -> framework param trees, sharded at load time.

The reference never loads weights (its models live behind the OpenAI API,
SURVEY.md §0). For in-framework decode we map HuggingFace checkpoint layouts
onto our ``models/transformer.py`` tree:

- llama/mistral layout: ``model.layers.{i}.self_attn.q_proj.weight`` etc.,
  weights stored [out, in] -> transposed into our [in, out] kernels.
- gemma layout: llama-like, tied embeddings, and RMSNorm stored as
  ``weight`` with output ``x * (1 + weight)`` -> our ``scale = 1 + weight``.
- gpt2 layout: ``h.{i}.attn.c_attn`` Conv1D (already [in, out], no transpose)
  holding fused QKV -> split three ways; learned ``wpe`` positions.

Memory discipline for 70B-class checkpoints: tensors are streamed one at a
time via ``safetensors.safe_open`` and, when a mesh is given, each tensor is
``jax.device_put`` onto its NamedSharding immediately — the host never holds
more than one full tensor, and each device only materializes its shard.
"""

from __future__ import annotations

import json
import logging
import os
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from fairness_llm_tpu.models.configs import ModelConfig
from fairness_llm_tpu.parallel import sharding as shd

logger = logging.getLogger(__name__)


# ---------------------------------------------------------------------------
# Name mapping: our param path -> (hf name, transform)
# ---------------------------------------------------------------------------

Transform = Callable[[jnp.ndarray], jnp.ndarray]


def _t(x: jnp.ndarray) -> jnp.ndarray:
    return x.T


def _ident(x: jnp.ndarray) -> jnp.ndarray:
    return x


def _llama_map(cfg: ModelConfig) -> Dict[str, Tuple[str, Transform]]:
    """Also covers mistral (identical naming) and, with tweaks below, gemma."""
    m: Dict[str, Tuple[str, Transform]] = {
        "embedding": ("model.embed_tokens.weight", _ident),
        "final_norm/scale": ("model.norm.weight", _ident),
    }
    if not cfg.tie_embeddings:
        m["lm_head"] = ("lm_head.weight", _t)
    for i in range(cfg.num_layers):
        p = f"layer_{i}"
        h = f"model.layers.{i}"
        m[f"{p}/attn_norm/scale"] = (f"{h}.input_layernorm.weight", _ident)
        m[f"{p}/mlp_norm/scale"] = (f"{h}.post_attention_layernorm.weight", _ident)
        for ours, theirs in (("q_proj", "q_proj"), ("k_proj", "k_proj"),
                             ("v_proj", "v_proj"), ("o_proj", "o_proj")):
            m[f"{p}/attn/{ours}/kernel"] = (f"{h}.self_attn.{theirs}.weight", _t)
        for ours, theirs in (("gate_proj", "gate_proj"), ("up_proj", "up_proj"),
                             ("down_proj", "down_proj")):
            m[f"{p}/mlp/{ours}/kernel"] = (f"{h}.mlp.{theirs}.weight", _t)
    return m


def _gemma_map(cfg: ModelConfig) -> Dict[str, Tuple[str, Transform]]:
    plus_one: Transform = lambda x: x + 1.0  # noqa: E731 — gemma RMSNorm convention
    m = _llama_map(cfg)
    for key, (name, tf) in list(m.items()):
        if key.endswith("norm/scale"):
            m[key] = (name, plus_one)
    return m


def _qwen2_map(cfg: ModelConfig) -> Dict[str, Tuple[str, Transform]]:
    """Qwen2: llama naming plus biases on the q/k/v projections only."""
    m = _llama_map(cfg)
    for i in range(cfg.num_layers):
        p, h = f"layer_{i}", f"model.layers.{i}"
        for proj in ("q_proj", "k_proj", "v_proj"):
            m[f"{p}/attn/{proj}/bias"] = (f"{h}.self_attn.{proj}.bias", _ident)
    return m


def _gpt2_map(cfg: ModelConfig) -> Dict[str, Tuple[str, Transform]]:
    """GPT-2 Conv1D stores [in, out]; c_attn fuses qkv along the out axis."""
    d = cfg.d_model

    def _qkv(part: int) -> Transform:
        return lambda x: x[..., part * d:(part + 1) * d]

    m: Dict[str, Tuple[str, Transform]] = {
        "embedding": ("wte.weight", _ident),
        "pos_embedding": ("wpe.weight", _ident),
        "final_norm/scale": ("ln_f.weight", _ident),
        "final_norm/bias": ("ln_f.bias", _ident),
    }
    for i in range(cfg.num_layers):
        p = f"layer_{i}"
        h = f"h.{i}"
        m[f"{p}/attn_norm/scale"] = (f"{h}.ln_1.weight", _ident)
        m[f"{p}/attn_norm/bias"] = (f"{h}.ln_1.bias", _ident)
        m[f"{p}/mlp_norm/scale"] = (f"{h}.ln_2.weight", _ident)
        m[f"{p}/mlp_norm/bias"] = (f"{h}.ln_2.bias", _ident)
        for j, proj in enumerate(("q_proj", "k_proj", "v_proj")):
            m[f"{p}/attn/{proj}/kernel"] = (f"{h}.attn.c_attn.weight", _qkv(j))
            m[f"{p}/attn/{proj}/bias"] = (f"{h}.attn.c_attn.bias", _qkv(j))
        m[f"{p}/attn/o_proj/kernel"] = (f"{h}.attn.c_proj.weight", _ident)
        m[f"{p}/attn/o_proj/bias"] = (f"{h}.attn.c_proj.bias", _ident)
        m[f"{p}/mlp/up_proj/kernel"] = (f"{h}.mlp.c_fc.weight", _ident)
        m[f"{p}/mlp/up_proj/bias"] = (f"{h}.mlp.c_fc.bias", _ident)
        m[f"{p}/mlp/down_proj/kernel"] = (f"{h}.mlp.c_proj.weight", _ident)
        m[f"{p}/mlp/down_proj/bias"] = (f"{h}.mlp.c_proj.bias", _ident)
    return m


_FAMILY_MAPS = {
    "llama": _llama_map,
    "mistral": _llama_map,
    "gemma": _gemma_map,
    "gpt2": _gpt2_map,
    "qwen": _qwen2_map,
}


def family_of(cfg: ModelConfig) -> str:
    name = cfg.name.lower()
    for fam in ("llama", "mistral", "gemma", "gpt2", "qwen"):
        if fam in name.replace("-", ""):
            return fam
    # tiny test configs: pick by flags
    if cfg.qkv_bias:
        return "qwen"
    return "gpt2" if cfg.pos_emb == "learned" else "llama"


def hf_name_map(cfg: ModelConfig, family: Optional[str] = None) -> Dict[str, Tuple[str, Transform]]:
    family = family or family_of(cfg)
    return _FAMILY_MAPS[family](cfg)


# ---------------------------------------------------------------------------
# Loading
# ---------------------------------------------------------------------------


def _strip_prefix(name: str, tensors: Dict[str, str]) -> str:
    """HF checkpoints sometimes prefix everything with 'transformer.' (gpt2)
    or 'model.' is already in our map; resolve against what's present."""
    if name in tensors:
        return name
    for prefix in ("transformer.", "model."):
        cand = prefix + name
        if cand in tensors:
            return cand
    raise KeyError(f"tensor '{name}' not found in checkpoint (have {len(tensors)} tensors)")


def _checkpoint_index(path: str) -> Dict[str, str]:
    """Map tensor name -> shard file for a safetensors checkpoint directory."""
    index_file = os.path.join(path, "model.safetensors.index.json")
    if os.path.exists(index_file):
        with open(index_file) as f:
            return json.load(f)["weight_map"]
    single = [f for f in os.listdir(path) if f.endswith(".safetensors")]
    if not single:
        raise FileNotFoundError(f"no .safetensors files under {path}")
    out: Dict[str, str] = {}
    from safetensors import safe_open

    for fname in single:
        with safe_open(os.path.join(path, fname), framework="flax") as f:
            for k in f.keys():
                out[k] = fname
    return out


def load_checkpoint(
    cfg: ModelConfig,
    path: str,
    mesh: Optional[jax.sharding.Mesh] = None,
    family: Optional[str] = None,
    dtype: Optional[Any] = None,
    verify: bool = True,
) -> Any:
    """Load an HF safetensors checkpoint dir into our param tree.

    With ``mesh``, each tensor is placed onto its tensor-parallel NamedSharding
    as it streams off disk; without, tensors land on the default device.

    ``verify`` (default on) checks the directory's sha256 ``manifest.json``
    (``integrity/manifest.py``) before any tensor is read: a bit-flipped or
    truncated shard raises ``IntegrityError`` naming the file, instead of
    loading garbage weights that decode plausible-looking garbage text.
    Directories without a manifest load unverified (pre-manifest
    checkpoints), with a debug note.
    """
    from safetensors import safe_open

    if verify:
        from fairness_llm_tpu.integrity.manifest import maybe_verify_manifest

        maybe_verify_manifest(path, kind="weights")
    dtype = dtype or (jnp.bfloat16 if cfg.dtype == "bfloat16" else jnp.float32)
    name_map = hf_name_map(cfg, family)
    weight_map = _checkpoint_index(path)
    shardings = shd.param_shardings(cfg, mesh) if mesh is not None else None
    quant = cfg.weight_quant == "int8"

    handles: Dict[str, Any] = {}

    def get_tensor(hf_name: str) -> jnp.ndarray:
        hf_name = _strip_prefix(hf_name, weight_map)
        fname = weight_map[hf_name]
        if fname not in handles:
            handles[fname] = safe_open(os.path.join(path, fname), framework="flax")
        return handles[fname].get_tensor(hf_name)

    def place(our_path: str, x: Any) -> None:
        leaf_sharding = _tree_get(shardings, our_path) if shardings is not None else None
        x = jax.device_put(x, leaf_sharding) if leaf_sharding is not None else jnp.asarray(x)
        _tree_set(params, our_path, x)

    params: Dict[str, Any] = {}
    try:
        for our_path, (hf_name, transform) in name_map.items():
            x = transform(get_tensor(hf_name))
            if quant and _quant_base(our_path) is not None:
                # int8 serving: quantize each matmul kernel AS IT STREAMS off
                # disk — the float tensor exists one at a time; HBM (and for
                # 70B-class checkpoints, host RAM) never holds a float tree.
                from fairness_llm_tpu.ops.quant_matmul import quantize_weight

                base = _quant_base(our_path)
                q, s = quantize_weight(jnp.asarray(x))
                place(f"{base}/kernel_q", q)
                place(f"{base}/kernel_scale", s)
                logger.debug("loaded %s <- %s %s (int8)", base, hf_name, q.shape)
                continue
            place(our_path, x.astype(dtype))
            logger.debug("loaded %s <- %s %s", our_path, hf_name, x.shape)
    finally:
        # Shard handles hold open fds + mmaps; a multi-shard 70B checkpoint
        # must not keep them alive until interpreter GC.
        for h in handles.values():
            h.__exit__(None, None, None)
    return params


def _quant_base(our_path: str) -> Optional[str]:
    """For a float-tree kernel path, the QuantDense module base path that
    replaces it under ``weight_quant='int8'`` — else None. Quantizable =
    every 2D matmul kernel: DenseGeneral ``.../kernel`` and the untied
    ``lm_head``; embeddings (gathered, not streamed whole), norms, and
    biases stay float."""
    if our_path.endswith("/kernel"):
        return our_path[: -len("/kernel")]
    if our_path == "lm_head":
        return "lm_head"
    return None


def quantize_params(params: Any) -> Any:
    """Float param tree -> the ``weight_quant='int8'`` tree layout.

    For tests and for quantizing in-memory weights (e.g. after fine-tuning);
    ``load_checkpoint`` quantizes tensor-at-a-time off disk instead.
    """
    from fairness_llm_tpu.ops.quant_matmul import quantize_weight

    out = _copy_tree(params)
    for path in list(_walk_paths(out)):
        base = _quant_base(path)
        if base is None:
            continue
        q, s = quantize_weight(jnp.asarray(_tree_get(out, path)))
        node = out
        parts = path.split("/")
        for part in parts[:-1]:
            node = node[part]
        del node[parts[-1]]
        _tree_set(out, f"{base}/kernel_q", q)
        _tree_set(out, f"{base}/kernel_scale", s)
    return out


def dequantize_params(params: Any, dtype=jnp.float32) -> Any:
    """Inverse of ``quantize_params`` (up to quantization rounding)."""
    from fairness_llm_tpu.ops.quant_matmul import dequantize_weight

    out = _copy_tree(params)
    for path in list(_walk_paths(out)):
        if not path.endswith("/kernel_q"):
            continue
        base = path[: -len("/kernel_q")]
        module = _tree_get(out, base)  # the QuantDense param dict
        w = dequantize_weight(
            jnp.asarray(module["kernel_q"]), jnp.asarray(module["kernel_scale"]), dtype
        )
        # Remove only the quant leaves — siblings (qwen2/gpt2 biases) stay.
        del module["kernel_q"], module["kernel_scale"]
        if base == "lm_head" and not module:
            # lm_head is a bare param leaf in the float tree, not a module
            parts = base.split("/")
            node = out
            for part in parts[:-1]:
                node = node[part]
            node[parts[-1]] = w
        else:
            _tree_set(out, f"{base}/kernel", w)
    return out


def _copy_tree(tree: Any) -> Any:
    """Structure-copy of a nested dict (leaves shared, dicts fresh)."""
    return {
        k: _copy_tree(v) if isinstance(v, dict) else v for k, v in tree.items()
    }


def _walk_paths(tree: Any, prefix: str = "") -> Any:
    for key, val in tree.items():
        path = f"{prefix}/{key}" if prefix else key
        if isinstance(val, dict):
            yield from _walk_paths(val, path)
        else:
            yield path


def save_checkpoint_hf(cfg: ModelConfig, params: Any, path: str, family: Optional[str] = None) -> None:
    """Inverse mapping: write our params as an HF-layout safetensors file.

    Used by tests (fabricate a checkpoint, round-trip it) and for exporting.
    Fused tensors (gpt2 c_attn) are reassembled from their parts; int8 trees
    export dequantized (HF layouts have no per-channel-int8 convention we
    target).
    """
    from safetensors.flax import save_file

    if cfg.weight_quant == "int8":
        params = dequantize_params(params)
    name_map = hf_name_map(cfg, family)
    family = family or family_of(cfg)
    out: Dict[str, jnp.ndarray] = {}
    fused: Dict[str, list] = {}
    for our_path, (hf_name, _tf) in name_map.items():
        x = _tree_get(params, our_path)
        if x is None:
            continue
        x = jnp.asarray(x)
        if family == "gpt2":
            if ".c_attn." in hf_name:
                fused.setdefault(hf_name, [None, None, None])
                part = {"q_proj": 0, "k_proj": 1, "v_proj": 2}[our_path.split("/")[-2]]
                fused[hf_name][part] = x
                continue
            out[hf_name] = x  # Conv1D: already [in, out]
        elif hf_name.endswith("norm.weight") and family == "gemma":
            out[hf_name] = x - 1.0
        elif x.ndim == 2 and not hf_name.endswith(("embed_tokens.weight", "wte.weight", "wpe.weight")):
            out[hf_name] = x.T
        else:
            out[hf_name] = x
    for hf_name, parts in fused.items():
        out[hf_name] = jnp.concatenate(parts, axis=-1)
    os.makedirs(path, exist_ok=True)
    save_file(out, os.path.join(path, "model.safetensors"))
    # Verified-artifact manifest (integrity/manifest.py): per-file sha256 +
    # tensor shape/dtype summary, checked by load_checkpoint. Covers every
    # file present at save time; files added later (tokenizer, provenance)
    # simply go unlisted and unverified.
    from fairness_llm_tpu.integrity.manifest import write_manifest

    write_manifest(path)


def _tree_get(tree: Any, path: str) -> Any:
    node = tree
    for part in path.split("/"):
        if not isinstance(node, dict) or part not in node:
            return None
        node = node[part]
    return node


def _tree_set(tree: Dict, path: str, value: Any) -> None:
    parts = path.split("/")
    node = tree
    for part in parts[:-1]:
        node = node.setdefault(part, {})
    node[parts[-1]] = value

"""Batched sequence scoring: per-token log-likelihoods under a model.

The decode engine answers "what would the model say"; scoring answers "how
likely is this text" — needed for perplexity-style model comparison (a
natural extension of the reference's phase-2 cross-MODEL evaluation, which
only compares rankings) and for calibration confidences that are real instead
of the reference's simulated ``1 - 0.05*rank`` (``phase3_facter_mitigation.py:126``).

One jitted forward per bucketed shape; mesh-sharded exactly like the decode
path. For sequences longer than one chip's memory, the sp axis applies (the
model's attention runs ring-style via GSPMD when activations are
seq-sharded).
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Dict, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from fairness_llm_tpu.runtime.engine import DecodeEngine, _bucket_batch, _bucket_len

# Cap on the [batch, s, vocab] f32 logits tensor one scoring forward may
# materialize; larger sweeps halve-and-recurse (module-level so tests can
# shrink it to exercise the chunked path with tiny models).
LOGITS_BUDGET_BYTES = 4e9


@dataclasses.dataclass
class ScoreOutput:
    log_likelihoods: np.ndarray  # [N] sum log p(token | prefix) over real tokens
    token_counts: np.ndarray  # [N] number of scored tokens
    mean_logprobs: np.ndarray  # [N] log_likelihood / token_count


def _score_batch(
    engine: DecodeEngine, texts: Sequence[str], prefix_counts: np.ndarray
) -> ScoreOutput:
    """Shared teacher-forced scoring scaffold (encode, left-truncate, bucket,
    pad, jit-cache, mesh dispatch). ``prefix_counts[i]`` real tokens at the
    start of row i are conditioning context: their logprobs are excluded.
    ``score_texts`` is the prefix_counts=0 case; one compiled kernel serves
    both (prefix_counts is a traced argument)."""
    tb = engine.tokenizer.encode_batch(texts)
    max_len = engine.config.max_seq_len
    if tb.tokens.shape[1] > max_len:
        # Position tables/caches hold max_seq_len slots and out-of-range
        # gathers clamp silently under jit (same hazard engine.generate
        # guards); keep the most recent tokens, like the decode path.
        import logging

        logging.getLogger(__name__).warning(
            "scoring texts longer than max_seq_len=%d; left-truncating", max_len
        )
        # Left-truncation drops the EARLIEST tokens (prefix first): shrink
        # each row's remaining-prefix count so the continuation boundary
        # stays correct (positions restart at 0 within the kept window).
        orig_lens = tb.valid.sum(axis=1)
        tb = engine.tokenizer.encode_batch(texts, max_len=max_len)
        kept_lens = tb.valid.sum(axis=1)
        dropped = np.maximum(orig_lens - kept_lens, 0)
        prefix_counts = np.maximum(prefix_counts - dropped, 0)
    # Encoded + truncation-adjusted exactly once; chunking happens downstream
    # on the encoded rows (re-running this function on raw texts would apply
    # the prefix adjustment twice).
    return _score_encoded(engine, tb.tokens, tb.valid, np.asarray(prefix_counts))


def _score_encoded(
    engine: DecodeEngine, row_tokens: np.ndarray, row_valid: np.ndarray,
    prefix_counts: np.ndarray,
) -> ScoreOutput:
    """Forward + reduce over already-encoded rows, chunking for memory."""
    n = len(row_tokens)
    # Trim fully-pad leading columns (rows are left-padded) so a chunk of
    # short rows buckets to its own tight length.
    lead = int(np.argmax(row_valid.any(axis=0))) if row_valid.any() else 0
    row_tokens, row_valid = row_tokens[:, lead:], row_valid[:, lead:]
    max_len = engine.config.max_seq_len
    s = min(_bucket_len(max(row_tokens.shape[1], 1), engine.seq_bucket), max_len)
    batch = _bucket_batch(n, engine.mesh)

    # The forward materializes [batch, s, vocab] logits; cap that tensor so a
    # large scoring sweep (e.g. every (query, item) pair of phase 2's scored
    # ranking) chunks into several forwards instead of OOMing HBM. The budget
    # is PER DEVICE (~4 GB of f32 logits leaves room for params + activations
    # on a 16 GB chip); the batch axis shards over dp, so divide by it.
    dp = engine.mesh.shape.get("dp", 1) if engine.mesh is not None else 1
    logits_bytes = batch * s * engine.config.vocab_size * 4 // dp
    if logits_bytes > LOGITS_BUDGET_BYTES and n <= 8:
        # No sequence-axis chunking exists: a handful of maximum-length rows
        # against a huge vocab (qwen2 at 8k x 152k is ~40 GB of f32 logits)
        # can exceed the budget with nothing left to halve. Warn with the
        # numbers so an OOM here is diagnosable rather than mysterious.
        logging.getLogger(__name__).warning(
            "scoring %d row(s) of bucketed length %d x vocab %d needs ~%.1f GB "
            "of logits (> %.1f GB budget) and cannot chunk further on the "
            "batch axis — may OOM; shorten rows or reduce max_seq_len",
            n, s, engine.config.vocab_size, logits_bytes / 1e9,
            LOGITS_BUDGET_BYTES / 1e9,
        )
    if logits_bytes > LOGITS_BUDGET_BYTES and n > 8:
        half = n // 2
        a = _score_encoded(engine, row_tokens[:half], row_valid[:half], prefix_counts[:half])
        b = _score_encoded(engine, row_tokens[half:], row_valid[half:], prefix_counts[half:])
        return ScoreOutput(
            log_likelihoods=np.concatenate([a.log_likelihoods, b.log_likelihoods]),
            token_counts=np.concatenate([a.token_counts, b.token_counts]),
            mean_logprobs=np.concatenate([a.mean_logprobs, b.mean_logprobs]),
        )
    tokens = np.full((batch, s), engine.tokenizer.pad_id, dtype=np.int32)
    valid = np.zeros((batch, s), dtype=bool)
    prefixes = np.zeros((batch,), dtype=np.int32)
    w = row_tokens.shape[1]
    tokens[:n, s - w:] = row_tokens
    valid[:n, s - w:] = row_valid
    prefixes[:n] = prefix_counts

    key = (batch, s, "score")
    fn = engine._compiled.get(key)
    if fn is None:
        model = engine.model

        def run(params, tokens, valid, prefixes):
            positions = jnp.maximum(jnp.cumsum(valid.astype(jnp.int32), axis=1) - 1, 0)
            # Forward over the FULL bucketed length (keeps seq a flash-eligible
            # multiple); the last position's logits predict nothing and drop.
            logits, _ = model.apply(
                {"params": params}, tokens, positions, valid, left_padded=True
            )
            lg = logits[:, :-1]
            targets = tokens[:, 1:]
            tvalid = valid[:, :-1] & valid[:, 1:]
            # Score only targets whose real-token index is past the prefix.
            tvalid = tvalid & (positions[:, 1:] >= prefixes[:, None])
            # Gather-then-logsumexp instead of materializing a full [B, S, V]
            # log_softmax temp alongside the logits.
            picked_logit = jnp.take_along_axis(lg, targets[..., None], axis=-1)[..., 0]
            lse = jax.scipy.special.logsumexp(lg, axis=-1)
            picked = jnp.where(tvalid, picked_logit - lse, 0.0)
            return jnp.sum(picked, axis=1), jnp.sum(tvalid, axis=1)

        fn = jax.jit(run)
        engine._compiled[key] = fn

    tokens_j, valid_j = jnp.asarray(tokens), jnp.asarray(valid)
    prefixes_j = jnp.asarray(prefixes)
    if engine.mesh is not None:
        from fairness_llm_tpu.parallel import sharding as shd

        bs = shd.batch_sharding(engine.mesh)
        tokens_j = jax.device_put(tokens_j, bs)
        valid_j = jax.device_put(valid_j, bs)
        with engine.mesh, nn.logical_axis_rules(engine.rules):
            ll, counts = fn(engine.params, tokens_j, valid_j, prefixes_j)
    else:
        ll, counts = fn(engine.params, tokens_j, valid_j, prefixes_j)

    ll = np.asarray(jax.device_get(ll))[:n]
    counts = np.asarray(jax.device_get(counts))[:n]
    return ScoreOutput(
        log_likelihoods=ll,
        token_counts=counts,
        mean_logprobs=np.where(counts > 0, ll / np.maximum(counts, 1), 0.0),
    )


def score_texts(
    engine: DecodeEngine, texts: Sequence[str], seed: int = 0
) -> ScoreOutput:
    """Score each text's tokens under the engine's model (teacher-forced).
    ``seed`` is accepted for signature stability; scoring is deterministic."""
    return _score_batch(engine, texts, np.zeros(len(texts), dtype=np.int32))


def score_prompted_continuations(
    engine: DecodeEngine, prompts: Sequence[str], continuations: Sequence[str]
) -> ScoreOutput:
    """Per-row conditional scoring: row i scores log p(continuations[i] |
    prompts[i]). Generalizes ``score_continuations`` to many prompts in ONE
    batched forward — e.g. phase 2 scores every (query, item) pair of a
    multi-query ranking sweep as a single device program instead of one
    param-streaming dispatch per query."""
    if len(prompts) != len(continuations):
        raise ValueError("prompts and continuations must align")
    # Sweeps repeat a few unique prompts across many rows (Q listwise queries
    # x N items; one calibration context per profile) — encode each once.
    plen: Dict[str, int] = {}
    for p in prompts:
        if p not in plen:
            plen[p] = len(engine.tokenizer.encode(p))
    prefix_counts = np.array([plen[p] for p in prompts], dtype=np.int32)
    texts = [p + c for p, c in zip(prompts, continuations)]
    return _score_batch(engine, texts, prefix_counts)


def score_continuations(
    engine: DecodeEngine, prompt: str, continuations: Sequence[str]
) -> ScoreOutput:
    """Conditional scoring: log p(continuation | prompt) for each continuation.

    All continuations share one prompt prefix and score as ONE batched
    teacher-forced forward — the basis of phase 2's "scored" ranking method
    (rank items by model likelihood instead of parsing a generated ranking;
    no parse failures by construction). Only tokens whose real-token index is
    >= the prompt's token count contribute, so by the chain rule
    ``log p(prompt + c) = log p(prompt) + score_continuations(...)`` exactly
    for tokenizers where concatenation composes token-wise (byte-level always;
    BPE may merge across the boundary — then the split is approximate at the
    first continuation token). Rows longer than max_seq_len left-truncate the
    prefix first; the boundary shifts with it.
    """
    prefix_len = len(engine.tokenizer.encode(prompt))
    texts = [prompt + c for c in continuations]
    return _score_batch(
        engine, texts, np.full(len(texts), prefix_len, dtype=np.int32)
    )


def perplexity_by_model(
    engines: Dict[str, DecodeEngine], texts: Sequence[str]
) -> Dict[str, float]:
    """Cross-model comparison: corpus perplexity per model."""
    out = {}
    for name, engine in engines.items():
        sc = score_texts(engine, texts)
        total_lp = float(sc.log_likelihoods.sum())
        total_tok = int(sc.token_counts.sum())
        out[name] = float(np.exp(-total_lp / max(total_tok, 1)))
    return out

"""Pre-run prefix-reuse report for a sweep's prompt set.

Builds the phase-1-shaped prompt set (profile grid -> counterfactual
recommendation prompts, optionally the phase-3 fairness-aware variants),
tokenizes it, and SIMULATES the paged KV cache's radix index over the
prompts in sweep order — so the expected ``--paged-kv`` hit rate, the
longest-common-prefix histogram, and the block-size sensitivity are all
inspectable BEFORE paying for a run.

Usage:
    python tools/prefix_stats.py                  # stock grid, block 16
    python tools/prefix_stats.py --profiles 5 --block-size 32
    python tools/prefix_stats.py --phase 3 --variant smart
    python tools/prefix_stats.py --json stats.json

The simulation is exact for an arena large enough to never evict (every
prompt's full blocks stay cached); a real run with a tight ``--kv-blocks``
can only hit less. Tokenization is byte-level (``ByteTokenizer``) — real
checkpoints tokenize coarser, which SHIFTS absolute token counts but
barely moves the shared FRACTION (the quantity the hit rate rides on).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from collections import Counter

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from fairness_llm_tpu.config import default_config  # noqa: E402
from fairness_llm_tpu.data.movielens import load_movielens  # noqa: E402
from fairness_llm_tpu.data.profiles import (  # noqa: E402
    create_base_preferences,
    create_profile_grid,
    profile_pairs,
)
from fairness_llm_tpu.models.tokenizer import ByteTokenizer  # noqa: E402
from fairness_llm_tpu.pipeline.prompts import (  # noqa: E402
    divergence_stats,
    fairness_aware_prompt,
    recommendation_prompt,
)
from fairness_llm_tpu.serving.paged import RadixIndex  # noqa: E402


def simulate_radix(token_rows, block_size: int):
    """Replay the sweep order through a real RadixIndex (unbounded arena):
    per-prompt matched tokens exactly as ``PagedKV.admit`` would compute
    them (full shared blocks + the copy-on-write lead, capped at len-1)."""
    index = RadixIndex(block_size)
    next_block = 0
    matched_per_prompt = []
    for ids in token_rows:
        m = index.match(ids)
        matched = m.matched(block_size)
        matched_per_prompt.append(matched)
        n_full = len(ids) // block_size
        blocks = [n.block for n in m.nodes]
        while len(blocks) < n_full:
            blocks.append(next_block)
            next_block += 1
        held, _ = index.insert(ids, blocks, m.nodes)
        index.release(held)  # sweep rows release as they finish
        if m.cow_node is not None:
            index.release([m.cow_node])  # drop the CoW-source pin
    return matched_per_prompt


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--phase", type=int, choices=(1, 3), default=1)
    ap.add_argument("--profiles", type=int, default=None,
                    help="profiles per demographic combo (default: config)")
    ap.add_argument("--variant", default="conformal",
                    choices=("conformal", "smart", "aggressive"),
                    help="phase-3 prompt variant")
    ap.add_argument("--strategy", default="demographic_parity")
    ap.add_argument("--block-size", type=int, default=16,
                    help="KV block size to simulate (the sharing granularity)")
    ap.add_argument("--data-dir", default=None)
    ap.add_argument("--json", default=None, metavar="PATH",
                    help="also write the stats as JSON")
    args = ap.parse_args()

    config = default_config()
    data = load_movielens(args.data_dir or config.data_dir,
                          seed=config.random_seed)
    base = create_base_preferences(data, seed=config.random_seed)
    profiles = create_profile_grid(base, config, args.profiles)
    if args.phase == 1:
        prompts = [recommendation_prompt(p) for p in profiles]
    else:
        anonymize = args.variant in ("smart", "aggressive")
        prompts = [
            fairness_aware_prompt(
                recommendation_prompt(p, anonymize=anonymize),
                args.strategy if args.variant == "conformal"
                else "individual_fairness",
                aggressive=(args.variant == "aggressive"),
            )
            for p in profiles
        ]

    by_id = dict(zip((p.id for p in profiles), prompts))
    pair_stats = divergence_stats(
        [(by_id[a], by_id[b]) for a, b in profile_pairs(profiles)]
    )

    tok = ByteTokenizer(512)
    rows = [tok.encode(p) for p in prompts]
    matched = simulate_radix(rows, args.block_size)
    total = sum(len(r) for r in rows)
    hit = sum(matched)
    fracs = [m / len(r) for m, r in zip(matched, rows)]
    hist = Counter(int(f * 10) / 10 for f in fracs)

    stats = {
        "phase": args.phase,
        "num_prompts": len(prompts),
        "block_size": args.block_size,
        "total_tokens": total,
        "matched_tokens": hit,
        "expected_hit_ratio": hit / total if total else 0.0,
        "pair_divergence": pair_stats,
        "matched_fraction_histogram": {
            f"{k:.1f}": hist[k] for k in sorted(hist)
        },
    }
    print(f"prompts: {len(prompts)}   block size: {args.block_size}   "
          f"tokens: {total}")
    print(f"counterfactual pairs: {pair_stats['pairs']}   shared-prefix "
          f"fraction min/mean/max: {pair_stats['min_frac']:.3f} / "
          f"{pair_stats['mean_frac']:.3f} / {pair_stats['max_frac']:.3f}")
    print(f"expected --paged-kv hit ratio (sweep order, no eviction): "
          f"{stats['expected_hit_ratio']:.3f}")
    print("matched-prefix fraction histogram (per prompt):")
    n = len(prompts)
    for k in sorted(hist):
        bar = "#" * max(1, round(40 * hist[k] / n))
        print(f"  {k:>4.1f}-{k + 0.1:.1f}  {hist[k]:5d}  {bar}")
    if args.json:
        with open(args.json, "w", encoding="utf-8") as f:
            json.dump(stats, f, indent=2)
        print(f"wrote {args.json}")
    # The layout contract: pairs must share most of their bytes, or the
    # defining workload has nothing for the prefix cache to reuse.
    return 0 if (not pair_stats["pairs"]
                 or pair_stats["min_frac"] >= 0.5) else 1


if __name__ == "__main__":
    sys.exit(main())

"""Rollout drill: zero-downtime version upgrades under fire — the
end-to-end proof behind docs/RESILIENCE.md's upgrade-fault rows and the
``validate_telemetry --require-rollout`` CI gate.

What it does, in one process, deterministically:

A. CLEAN UPGRADE: serves a streaming workload through a 2-replica
   ``ReplicaSet`` while a ``RolloutController`` walks the fleet from v0
   to v1 (same weights, new version id) — canary-gated standby per wave,
   stepped traffic shift, planned retirement of each v0 replica —
   asserting ZERO lost requests, every stream token-for-token with the
   reference OF ITS PINNED VERSION (a request finishes on the version
   that admitted it), and the fleet entirely on v1 with the autoscaler
   arbitration counted;
B. CORRUPT NEW WEIGHTS: points the next rollout's ``engine_fn`` at a
   checkpoint with one flipped BIT — the manifest refuses the load
   during PREPARING, the rollout lands terminal ``rolled_back`` before
   any replica joins, live traffic never notices (all results ok,
   membership unchanged), and one ``rollout`` incident bundle names the
   manifest gate;
C. BIASED NEW VERSION: rolls toward an engine with DIFFERENT weights
   while byte-identical counterfactual pairs stream through the fleet.
   The moment the traffic split lands pair members on different
   versions, their outputs diverge — the FairnessMonitor's pair watch
   attributes the divergence to the new replica and the fairness
   deployment gate rolls the wave back mid-flight: every in-flight
   request on the fenced v+1 replica migrates back (migrated ==
   recovered), zero requests lost, EXACTLY one deduplicated ``rollout``
   bundle naming the fairness gate, and the fleet back to all-old
   healthy;
D. MID-ROLLOUT CRASH + RESUME: starts a journaled rollout, abandons the
   fleet mid-wave (the crash), then ``resume_serving(..., version=...)``
   replays the journal's unfinished requests on the OLD version — ids
   pinned to the half-deployed version are restamped and counted
   (``rollout_resume_restamped_total``), every resumed stream decodes
   single-version token-parity clean, and the journal drains empty: the
   wave is rolled back at resume, never a version-mixed migration;
E. validates the telemetry: ``rollout_transitions_total`` shows one
   ``complete`` and two ``rolled_back`` terminals, ``rollout_rollbacks_
   total`` carries the manifest + fairness causes, fleet migration
   counters balance, and the snapshot passes schema validation
   (``validate_telemetry --require-rollout`` gates exactly these).

Usage (CI runs exactly this):
    JAX_PLATFORMS=cpu python tools/rollout_drill.py --telemetry-dir tel
"""

from __future__ import annotations

import argparse
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from fairness_llm_tpu.config import (  # noqa: E402
    FleetConfig,
    IntegrityConfig,
    ModelSettings,
    ResilienceConfig,
    RolloutConfig,
    ServingConfig,
)
from fairness_llm_tpu.models.configs import get_model_config  # noqa: E402
from fairness_llm_tpu.resilience import ServingJournal, resume_serving  # noqa: E402
from fairness_llm_tpu.runtime.engine import DecodeEngine  # noqa: E402
from fairness_llm_tpu.serving import (  # noqa: E402
    ReplicaSet,
    Request,
    RolloutController,
)

GREEDY = ModelSettings(temperature=0.0, max_tokens=8)
SERVING = ServingConfig(enabled=True, num_slots=2, queue_capacity=64,
                        max_prompt_len=192, max_new_tokens=32, decode_chunk=4)
FLEET2 = FleetConfig(replicas=2, fence_cooldown_s=0.02)
RESILIENCE = ResilienceConfig(enabled=True, max_step_seconds=120.0,
                              breaker_threshold=1, breaker_cooldown_s=0.02)
INTEG = IntegrityConfig(canary_max_tokens=8)

PROMPTS = [
    "the quick brown fox",
    "hello there friend",
    "abc abc abc abc",
    "one two three one two",
    "recommend ten films please",
    "name five good books",
    "zz zz zz",
    "a longer prompt that shifts padding and lands in a bucket",
]

WALL_GUARD_S = 240.0  # per-section drive ceiling: a wedge fails loudly


def refs_for(engine) -> dict:
    """Greedy reference rows keyed by prompt — what any stream pinned to
    this engine's version must reproduce token-for-token."""
    return {p: np.asarray(engine.generate([p], GREEDY).tokens[0])
            for p in PROMPTS}


def parity_ok(res, ref) -> bool:
    got = np.asarray(res.tokens)
    n = len(got)
    return n > 0 and np.array_equal(got, ref[:n])


def drive(fleet, ro, reqs) -> dict:
    """Tick the fleet (which drives the rollout) while feeding ``reqs``,
    until the controller is terminal and every request has a Result."""
    results, pending = {}, list(reqs)
    t0 = time.monotonic()
    while True:
        if pending and fleet.submit(pending[0]):
            pending.pop(0)
        fleet.tick()
        for r in reqs:
            if r.id not in results:
                res = fleet.take_result(r.id)
                if res is not None:
                    results[r.id] = res
        if not ro.active and not pending and len(results) == len(reqs):
            break
        if time.monotonic() - t0 > WALL_GUARD_S:
            print(f"  drive wall guard hit: state={ro.state} "
                  f"results={len(results)}/{len(reqs)}")
            break
    return results


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--telemetry-dir", default=None,
                    help="write events.jsonl + the validated snapshot here")
    a = ap.parse_args()

    from fairness_llm_tpu import telemetry as T

    sink = T.configure(a.telemetry_dir) if a.telemetry_dir else None
    inc_dir = os.path.join(
        a.telemetry_dir or tempfile.mkdtemp(prefix="rollout-incidents-"),
        "incidents",
    )
    T.arm_incidents(inc_dir, cooldown_s=3600.0)

    problems = []

    def check(ok: bool, what: str) -> None:
        print(("PASS" if ok else "FAIL") + f"  {what}")
        if not ok:
            problems.append(what)

    def bundles(scope=None):
        found = [m for m in T.list_bundles(inc_dir)
                 if m["class"] == "rollout"]
        if scope is not None:
            found = [m for m in found if m.get("scope") == scope]
        return found

    # Harness-appropriate SLO targets (same stance as the chaos drill): a
    # tiny CPU model meets 60 s TTFT trivially, so the rollout SLO gate
    # only fires on REAL regressions, never on 1-vCPU compile stalls.
    from fairness_llm_tpu.telemetry.slo import SLOTargets, set_slo_targets

    set_slo_targets(SLOTargets(ttft_p95_s=60.0, e2e_p99_s=120.0))

    cfg = get_model_config("tiny-test")
    eng_v0 = DecodeEngine(cfg, seed=0)
    ref_v0 = refs_for(eng_v0)
    reg = T.get_registry()

    # -- A. clean v0 -> v1 upgrade under live streaming traffic -------------
    print("== A: clean upgrade ==")
    fleet = ReplicaSet(eng_v0, SERVING, settings=GREEDY, fleet=FLEET2,
                       resilience=RESILIENCE, integrity=INTEG)
    eng_v1 = DecodeEngine(cfg, seed=0)  # same weights, new version id
    ref_v1 = refs_for(eng_v1)
    ro = RolloutController(
        fleet, "v1", engine=eng_v1,
        config=RolloutConfig(enabled=True, canary_window_s=0.05,
                             traffic_steps=2),
    )
    ro.start()
    reqs_a = [Request(prompt=PROMPTS[i % len(PROMPTS)], id=f"a_q{i}",
                      settings=GREEDY) for i in range(len(PROMPTS) * 2)]
    res_a = drive(fleet, ro, reqs_a)
    check(ro.state == "complete",
          f"rollout reached complete (state={ro.state})")
    check(fleet.version == "v1"
          and all(r.version == "v1" and not r.fenced
                  for r in fleet.replicas)
          and len(fleet.replicas) == FLEET2.replicas,
          "fleet entirely on v1, all replicas healthy")
    check(len(res_a) == len(reqs_a),
          f"zero lost through the upgrade ({len(res_a)}/{len(reqs_a)} "
          "terminal)")
    par, pinned_counts = True, {}
    for r in reqs_a:
        res = res_a.get(r.id)
        if res is None:
            continue
        ver = fleet.request_version(r.id)
        pinned_counts[ver] = pinned_counts.get(ver, 0) + 1
        ref = (ref_v1 if ver == "v1" else ref_v0)[r.prompt]
        if not (res.ok and parity_ok(res, ref)):
            par = False
            print(f"  parity break: {r.id} pinned={ver}")
    check(par, "every stream ok + token-for-token with its PINNED "
               f"version's reference (pins: {pinned_counts})")
    check(reg.read_value("rollout_transitions_total", component="rollout",
                         to="complete") == 1,
          "one terminal complete transition counted")
    check(reg.read_value("rollout_autoscale_paused_total",
                         component="rollout", default=0.0) >= 0.0,
          "autoscaler arbitration surface present")

    # -- B. corrupt v+1 weights: manifest refusal, zero user impact ---------
    print("== B: corrupt new weights ==")
    from fairness_llm_tpu.runtime.weights import (  # noqa: E402
        load_checkpoint,
        save_checkpoint_hf,
    )
    from fairness_llm_tpu.utils.failures import ScriptedFaultInjector  # noqa: E402

    wdir = tempfile.mkdtemp(prefix="rollout-weights-")
    save_checkpoint_hf(eng_v0.config, eng_v0.params, wdir)
    shard = os.path.join(wdir, "model.safetensors")
    ScriptedFaultInjector.flip_bit(shard, (os.path.getsize(shard) - 64) * 8)

    def poisoned_engine():
        # The manifest check inside load_checkpoint raises IntegrityError
        # on the flipped shard — the engine below is never built.
        params = load_checkpoint(eng_v0.config, wdir)
        eng = DecodeEngine(eng_v0.config, seed=0)
        eng.params = params
        return eng

    members_before = {r.name for r in fleet.replicas}
    ro_b = RolloutController(
        fleet, "v2", engine_fn=poisoned_engine,
        config=RolloutConfig(enabled=True, canary_window_s=0.05,
                             traffic_steps=2),
    )
    ro_b.start()
    reqs_b = [Request(prompt=p, id=f"b_q{i}", settings=GREEDY)
              for i, p in enumerate(PROMPTS)]
    res_b = drive(fleet, ro_b, reqs_b)
    check(ro_b.state == "rolled_back"
          and (ro_b.cause or "").startswith("manifest"),
          f"corrupt weights refused during preparing (cause={ro_b.cause})")
    check({r.name for r in fleet.replicas} == members_before
          and fleet.version == "v1",
          "zero membership churn: no v2 replica ever joined")
    check(len(res_b) == len(reqs_b) and all(
              r.ok and parity_ok(r, ref_v1[q.prompt])
              for q, r in ((q, res_b[q.id]) for q in reqs_b)),
          "zero user impact: every request served clean on v1 throughout")
    b_bundles = bundles(scope="fleet:v2")
    check(len(b_bundles) == 1 and "manifest" in b_bundles[0]["cause"],
          "one rollout bundle naming the manifest gate")

    # -- C. biased v+1: fairness deployment gate rolls back mid-wave --------
    print("== C: biased new version ==")
    from fairness_llm_tpu.telemetry.fairness import get_fairness_monitor  # noqa: E402

    eng_biased = DecodeEngine(cfg, seed=7)  # different weights: the "bias"
    mon = get_fairness_monitor()
    mon.begin_study()
    migrated_before = reg.read_value("fleet_migrated_requests_total",
                                     component="fleet", default=0.0)
    ro_c = RolloutController(
        fleet, "v3", engine=eng_biased,
        config=RolloutConfig(enabled=True, canary_window_s=0.6,
                             traffic_steps=4, abort_on_fairness_alert=True),
    )
    ro_c.start()

    # Byte-identical counterfactual pairs, streamed one per tick while
    # the wave shifts traffic: the moment members land on different
    # versions their bytes diverge and the pair watch attributes the new
    # replica. Feeding stops once the controller is terminal; the loop
    # then drains every outstanding stream.
    all_c: list = []
    outstanding_c: list = []
    res_c: dict = {}
    t0 = time.monotonic()
    i = 0
    while time.monotonic() - t0 < WALL_GUARD_S:
        if ro_c.active and i < 40:
            prompt = PROMPTS[i % len(PROMPTS)]
            for g in ("g_a", "g_b"):
                q = Request(prompt=prompt, id=f"c_p{i}_{g}",
                            settings=GREEDY, group=g, attribute="rollout",
                            pair_id=f"c_pair{i}")
                if fleet.submit(q):
                    all_c.append(q)
                    outstanding_c.append(q)
            i += 1
        fleet.tick()
        for q in list(outstanding_c):
            r = fleet.take_result(q.id)
            if r is not None:
                res_c[q.id] = r
                outstanding_c.remove(q)
        if not ro_c.active and not outstanding_c and not fleet.has_work:
            break
    check(ro_c.state == "rolled_back"
          and "pair_divergence" in (ro_c.cause or ""),
          f"fairness gate rolled the wave back (cause={ro_c.cause})")
    check(fleet.version == "v1" and len(fleet.replicas) == FLEET2.replicas
          and all(r.version == "v1" and not r.fenced
                  for r in fleet.replicas),
          "fleet back to all-v1 healthy after rollback")
    check(len(res_c) == len(all_c) and all(r.ok for r in res_c.values()),
          f"zero lost through the aborted wave ({len(res_c)}/{len(all_c)} "
          "terminal ok)")
    ref_biased = refs_for(eng_biased)
    par_c = True
    for q in all_c:
        res = res_c.get(q.id)
        if res is None:
            continue
        ver = fleet.request_version(q.id)
        ref = (ref_biased if ver == "v3" else ref_v1)[q.prompt]
        if not parity_ok(res, ref):
            par_c = False
            print(f"  parity break: {q.id} pinned={ver}")
    check(par_c, "every stream single-version token parity (v3-pinned "
                 "streams match the biased reference, never a mix)")
    migrated = reg.read_value("fleet_migrated_requests_total",
                              component="fleet", default=0.0)
    recovered = reg.read_value("fleet_migrated_recovered_total",
                               component="fleet", default=0.0)
    check(migrated == recovered,
          f"migrated == recovered across the rollback ({migrated:g} == "
          f"{recovered:g})")
    c_bundles = bundles(scope="fleet:v3")
    check(len(c_bundles) == 1 and "pair_divergence" in c_bundles[0]["cause"],
          "exactly one deduplicated rollout bundle naming the fairness "
          "gate")
    check(reg.read_value("rollout_rollbacks_total", component="rollout",
                         cause="pair_divergence") == 1,
          "rollback cause counted under the fairness gate")

    # -- D. mid-rollout crash + resume on the old version -------------------
    print("== D: mid-rollout crash + resume ==")
    jdir = tempfile.mkdtemp(prefix="rollout-journal-")
    journal = ServingJournal(jdir)
    fleet_d = ReplicaSet(eng_v0, SERVING, settings=GREEDY, fleet=FLEET2,
                         resilience=RESILIENCE, integrity=INTEG,
                         journal=journal)
    ro_d = RolloutController(
        fleet_d, "v1", engine=DecodeEngine(cfg, seed=0),
        config=RolloutConfig(enabled=True, canary_window_s=5.0,
                             traffic_steps=2),
    )
    ro_d.start()
    reqs_d = [Request(prompt=PROMPTS[i % len(PROMPTS)], id=f"d_q{i}",
                      settings=GREEDY) for i in range(48)]
    t0 = time.monotonic()
    di, staged = 0, False
    while time.monotonic() - t0 < WALL_GUARD_S:
        # One submission per tick: traffic keeps arriving WHILE the wave
        # shifts, so the error-diffusion steering pins some of it to the
        # half-deployed v1 replica.
        if di < len(reqs_d) and fleet_d.submit(reqs_d[di]):
            di += 1
        fleet_d.tick()
        if ro_d.state == "shifting" and any(
                s.get("version") == "v1" for s in journal.unfinished()):
            # The crash point: mid-wave, with journaled-but-unfinished
            # work pinned to the new version.
            staged = True
            break
        if not ro_d.active:
            break  # completed before staging — the check below fails
    check(staged, "crash staged mid-wave with journaled work pinned to "
                  "the half-deployed v1")
    # The "crash": the fleet is abandoned — no drain, no terminal records
    # for in-flight work. The journal is all that survives.
    del fleet_d

    unfinished = journal.unfinished()
    v1_unfinished = [s["id"] for s in unfinished
                     if s.get("version") == "v1"]
    restamp_before = reg.read_value("rollout_resume_restamped_total",
                                    component="rollout", default=0.0)
    resumed = resume_serving(eng_v0, journal, serving=SERVING,
                             resilience=RESILIENCE, version="v0")
    restamp_after = reg.read_value("rollout_resume_restamped_total",
                                   component="rollout", default=0.0)
    check(len(resumed) == len(unfinished) and all(
              r.ok and parity_ok(r, ref_v0[
                  next(q.prompt for q in reqs_d if q.id == rid)])
              for rid, r in resumed.items()),
          f"resume re-served all {len(unfinished)} unfinished request(s) "
          "token-parity clean on v0")
    check(restamp_after - restamp_before == len(v1_unfinished),
          f"every v1-pinned unfinished id restamped at resume "
          f"({len(v1_unfinished)} counted): wave rolled back, no "
          "version-mixed migration")
    check(not journal.unfinished(), "journal drained empty after resume")
    # Resolve the crashed controller's state machine: the resume on v0 IS
    # the rollback — resume tooling stamps the terminal verdict so the
    # snapshot never shows a rollout abandoned mid-wave.
    ro_d.resolve_crashed("resumed on v0 after mid-wave crash")
    check(ro_d.state == "rolled_back",
          "crashed rollout resolved terminal: wave rolled back at resume")

    # -- E. telemetry acceptance --------------------------------------------
    print("== E: telemetry ==")
    snap = T.snapshot(reg)
    trans = {c["labels"].get("to"): c["value"] for c in snap["counters"]
             if c["name"] == "rollout_transitions_total"}
    check(trans.get("complete", 0) >= 1 and trans.get("rolled_back", 0) >= 2,
          f"terminal transitions counted (complete={trans.get('complete')}"
          f", rolled_back={trans.get('rolled_back')})")
    causes = {c["labels"].get("cause") for c in snap["counters"]
              if c["name"] == "rollout_rollbacks_total" and c["value"] > 0}
    check({"manifest", "pair_divergence"} <= causes,
          f"rollback causes cover the manifest + fairness gates ({causes})")
    if a.telemetry_dir:
        path = T.write_snapshot(reg, a.telemetry_dir)
        bad = T.validate_snapshot(T.load_snapshot(path))
        check(not bad, f"snapshot schema valid ({path})")
        if sink is not None:
            T.install_event_sink(None)
            sink.close()

    print(f"\nrollout drill: {'PASS' if not problems else 'FAIL'} "
          f"({len(problems)} problem(s))")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())

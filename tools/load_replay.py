"""Load-replay drill: trace-driven synthetic traffic with SLO-coupled
autoscaling — the elastic-fleet proof behind docs/SERVING.md §Elastic
fleet (ISSUE 11).

What it does, in one process, deterministically:

1. generates a seeded synthetic trace (``serving/replay.py``): a diurnal
   session-arrival curve with one flash-crowd burst, heavy-tailed session
   lengths over a million-user id space, and a mixed interactive/batch QoS
   population — then regenerates it and asserts the JSONL is
   byte-identical (same seed -> same trace, half one of the determinism
   contract);
2. replays the trace time-compressed against a ``ReplicaSet`` that starts
   at ONE replica with the autoscaler armed: the burst drives the
   fast-window SLO burn up, the controller adds canary-gated standby
   replicas (which must then actually serve traffic), and a
   ``replica_crashes_at`` schedule kills the first standby in the middle
   of the burst — fence, zero-grace drain, journal migration, canary-gated
   rejoin, all under live replayed load;
3. rides the quiet post-burst tail until the controller retires the
   surplus replicas through the drain/migration path — the full elastic
   cycle (up AND down) in one replay;
4. asserts the zero-loss ledger: every accepted event reached a terminal
   Result (``lost == 0``), migrated == recovered, the journal holds no
   unfinished record, and the final fleet is whole
   (``fleet_healthy_replicas == fleet_replicas``);
5. asserts TOKEN PARITY for every completed request against the static
   engine (one baseline decode per unique (prompt, budget) combo) — so
   migrated and retired-replica survivors provably decoded the same
   stream the engine alone would have;
6. replays a second, fault-free same-seed trace TWICE on fresh fleets and
   asserts the two runs admitted the identical request set and produced
   the identical token map (half two of the determinism contract: a
   same-seed re-run reproduces the admitted-token set exactly);
7. floods a deliberately under-provisioned one-replica fleet (tiny
   queue, autoscaler pinned at 1) with a burst trace so the shed ladder
   refuses admissions WITH retry-after advice, and asserts the driver
   honored the advice (``replay_retry_after_honored_total`` >= 1: the
   replay client backs off and re-offers instead of hammering the gate)
   while the zero-loss ledger still closes (``lost == 0`` — every
   honored retry ends in a terminal Result or a recorded re-shed);
8. writes the telemetry snapshot for
   ``tools/validate_telemetry.py --require-autoscale`` (>=1 scale-up,
   >=1 scale-down, replay accepted == terminal, migrated == recovered,
   final fleet healthy).

Usage (CI runs exactly this):
    JAX_PLATFORMS=cpu python tools/load_replay.py --telemetry-dir replay-tel
"""

from __future__ import annotations

import argparse
import dataclasses
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from fairness_llm_tpu.config import (  # noqa: E402
    AutoscaleConfig,
    FleetConfig,
    IntegrityConfig,
    ModelSettings,
    OverloadConfig,
    ResilienceConfig,
    ServingConfig,
)
from fairness_llm_tpu.models.configs import get_model_config  # noqa: E402
from fairness_llm_tpu.resilience import ServingJournal  # noqa: E402
from fairness_llm_tpu.runtime.engine import DecodeEngine  # noqa: E402
from fairness_llm_tpu.serving import (  # noqa: E402
    ReplayDriver,
    ReplicaSet,
    TraceConfig,
    generate_trace,
    write_trace,
)
from fairness_llm_tpu.serving.replay import DEFAULT_PROMPTS  # noqa: E402
from fairness_llm_tpu.telemetry.slo import SLOTargets, set_slo_targets  # noqa: E402
from fairness_llm_tpu.utils.failures import ScriptedFaultInjector  # noqa: E402

GREEDY = ModelSettings(temperature=0.0, max_tokens=16)
SERVING = ServingConfig(enabled=True, num_slots=2, queue_capacity=16,
                        max_prompt_len=96, max_new_tokens=16, decode_chunk=4)
RESILIENCE = ResilienceConfig(enabled=True, breaker_threshold=2,
                              breaker_cooldown_s=0.05)
# Harness-shaped SLO targets: the off-burst load meets a 0.4 s TTFT on the
# tiny CPU engine; the burst's queueing blows through it, which is exactly
# the burn the autoscaler exists to act on. A short fast window lets the
# burn decay within the compressed quiet tail.
SLO = SLOTargets(ttft_p95_s=0.4, e2e_p99_s=30.0, error_rate=0.02,
                 fast_window_s=2.0, slow_window_s=20.0)

# The drill's prompt catalog — the module's own sweep-shaped default,
# truncated (six shapes keep the compiled-bucket count small on CPU).
PROMPTS = DEFAULT_PROMPTS[:6]


def trace_config(seed: int, duration: float, burst: bool) -> TraceConfig:
    bursts = ((duration / 3.0, duration / 4.0, 8.0),) if burst else ()
    return TraceConfig(
        seed=seed, duration_s=duration, users=1_000_000,
        base_sessions_per_s=0.5, diurnal_amplitude=0.5,
        diurnal_period_s=duration,  # one "day" spans the trace
        bursts=bursts, session_tail_alpha=1.3, session_max_turns=4,
        think_time_s=3.0, interactive_frac=0.8,
        max_tokens_choices=(4, 6, 8),
    )


def build_fleet(engine, journal=None, injector=None, max_replicas=3,
                compression=4.0, overload=True, name=None) -> ReplicaSet:
    ov = OverloadConfig(
        enabled=True,
        # Time-dependent knobs scale with the compression factor, the same
        # way the driver scales request deadlines: 5 trace-seconds of
        # queue aging is 5/c wall seconds at compression c.
        aging_s=5.0 / compression,
        healthy_window_s=2.0 / compression,
        deadline_admission=False,  # the smoke trace carries no deadlines
        queue_window_s=1.0, eval_interval_s=0.1,
        burn_threshold=8.0,  # the autoscaler acts first; shedding is the
        retry_after_s=0.2,   # last resort at this drill's offered load
    ) if overload else None
    return ReplicaSet(
        engine, SERVING, settings=GREEDY,
        fleet=FleetConfig(replicas=1, fence_cooldown_s=0.3),
        resilience=RESILIENCE,
        journal=journal, fault_injector=injector,
        integrity=IntegrityConfig(canary_max_tokens=8),
        overload=ov, name=name,
        autoscale=AutoscaleConfig(
            enabled=True, min_replicas=1, max_replicas=max_replicas,
            up_burn_threshold=2.0, up_queue_frac=0.75, up_overload_level=1,
            up_window_s=0.15, down_burn_threshold=0.5,
            down_queue_frac=0.1, down_load_frac=0.5, down_window_s=0.8,
            cooldown_s=0.4, eval_interval_s=0.05,
        ),
    )


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--telemetry-dir", default=None,
                    help="write events.jsonl + the validated snapshot here")
    ap.add_argument("--journal-dir", default=None,
                    help="serving journal dir (default: a temp dir)")
    ap.add_argument("--seed", type=int, default=11)
    ap.add_argument("--duration", type=float, default=60.0,
                    help="trace span in TRACE seconds (default 60)")
    ap.add_argument("--compression", type=float, default=4.0,
                    help="trace-to-wall time compression (default 4)")
    ap.add_argument("--max-replicas", type=int, default=3)
    ap.add_argument("--max-wall", type=float, default=240.0,
                    help="per-replay wall guard in seconds")
    ap.add_argument("--skip-determinism", action="store_true",
                    help="skip the same-seed re-run phase (faster)")
    a = ap.parse_args()

    from fairness_llm_tpu import telemetry as T

    sink = T.configure(a.telemetry_dir) if a.telemetry_dir else None
    journal_dir = a.journal_dir or tempfile.mkdtemp(prefix="replay-journal-")
    set_slo_targets(SLO)

    problems = []

    def check(ok: bool, what: str) -> None:
        print(("PASS" if ok else "FAIL") + f"  {what}")
        if not ok:
            problems.append(what)

    # -- 1. trace generation + byte determinism ------------------------------
    tcfg = trace_config(a.seed, a.duration, burst=True)
    events = generate_trace(tcfg, PROMPTS)
    lines = [ev.to_json() for ev in events]
    lines2 = [ev.to_json() for ev in generate_trace(tcfg, PROMPTS)]
    check(lines == lines2 and len(events) > 20,
          f"same seed -> byte-identical trace ({len(events)} events, "
          f"{a.duration:g} trace-s, "
          f"{sum(e.qos == 'interactive' for e in events)} interactive / "
          f"{sum(e.qos == 'batch' for e in events)} batch)")
    if a.telemetry_dir:
        write_trace(os.path.join(a.telemetry_dir, "replay_trace.jsonl"),
                    events, tcfg)

    # -- 2-4. the elastic replay ---------------------------------------------
    engine = DecodeEngine(get_model_config("tiny-test"), seed=0)
    journal = ServingJournal(journal_dir)
    # Crash the FIRST standby (r1) in the middle of the burst, in trace
    # time: the burst spans [duration/3, duration/3 + duration/4), so 50%
    # through the trace is deep inside it — r1 has joined and is holding
    # burst backlog, so the fence has live work to migrate.
    crash_t = 0.5 * a.duration
    injector = ScriptedFaultInjector(replica_crashes_at={"r1": crash_t})
    fleet = build_fleet(engine, journal=journal, injector=injector,
                        max_replicas=a.max_replicas,
                        compression=a.compression)
    driver = ReplayDriver(
        fleet, events, compression=a.compression, fault_injector=injector,
        max_wall_s=a.max_wall,
        # Quiet tail: long enough past the last arrival for the burn to
        # decay (fast window) and the scale-down hysteresis + cooldowns to
        # walk the fleet back to min_replicas.
        tail_s=0.8 * a.duration,
    )
    report = driver.run()
    print("replay:", report.summary())

    auto = fleet.autoscaler
    check(not report.timed_out, "replay finished inside the wall guard")
    check(report.lost == 0,
          f"zero accepted-then-lost ({report.accepted} accepted, "
          f"{report.terminal - report.gate_sheds} terminal)")
    check(auto.scale_ups >= 1,
          f"burst drove >=1 burn-driven scale-up ({auto.scale_ups})")
    check(auto.scale_downs >= 1,
          f"quiet tail drove >=1 scale-down ({auto.scale_downs})")
    check(len(fleet.replicas) == 1 and fleet.healthy_count == 1,
          f"fleet back to min_replicas and healthy "
          f"({len(fleet.replicas)} replicas, {fleet.healthy_count} "
          "healthy)")
    check(injector.replica_faults_fired == [("r1", "replica_crash")],
          f"scheduled replica crash fired at trace-t {crash_t:g} "
          f"({injector.replica_faults_fired})")
    reg = T.get_registry()
    fenced = reg.read_value("fleet_fenced_total", component="fleet",
                            replica="r1", reason="replica_crash")
    check(fenced >= 1, "crashed standby was fenced (fleet_fenced_total)")
    migrated = reg.read_value("fleet_migrated_requests_total",
                              component="fleet")
    recovered = reg.read_value("fleet_migrated_recovered_total",
                               component="fleet")
    check(migrated == recovered,
          f"migrated == recovered ({migrated:g} == {recovered:g})")
    served_r1 = sum(
        getattr(m, "value", 0) for m in reg.instruments()
        if getattr(m, "name", "") == "requests_finished_total"
        and getattr(m, "labels", {}).get("replica") == "r1"
    )
    check(served_r1 > 0,
          f"canary-gated standby r1 actually served traffic "
          f"({served_r1:g} requests finished)")
    unfinished = journal.unfinished()
    check(not unfinished,
          f"journal holds no unfinished record ({len(unfinished)})")

    # -- 5. token parity for EVERY completed request -------------------------
    by_id = {e.id: e for e in events}
    combos = sorted({(by_id[rid].prompt, by_id[rid].max_tokens)
                     for rid in report.tokens})
    baseline = {}
    for prompt, budget in combos:
        out = engine.generate(
            [prompt], dataclasses.replace(GREEDY, max_tokens=budget),
            share_prefix=False,
        )
        baseline[(prompt, budget)] = [
            int(t) for t in out.tokens[0] if t != engine.tokenizer.pad_id
        ]
    bad = []
    for rid, toks in report.tokens.items():
        ev = by_id[rid]
        ref = baseline[(ev.prompt, ev.max_tokens)]
        if list(toks) != ref[: len(toks)] or \
                len(toks) < min(len(ref), ev.max_tokens):
            bad.append(rid)
    check(not bad,
          f"token parity vs the static engine for all "
          f"{len(report.tokens)} completed requests "
          f"(incl. migrated/retired-replica survivors); mismatches: {bad[:4]}")

    # -- 6. same-seed re-run determinism -------------------------------------
    if not a.skip_determinism:
        det_cfg = trace_config(a.seed + 1, a.duration / 2.0, burst=False)
        det_events = generate_trace(det_cfg, PROMPTS)
        runs = []
        for run_idx in range(2):
            # Overload control OFF for the determinism phase: the claim is
            # "same seed -> identical admitted-token set", which needs an
            # under-capacity run where nothing sheds — backpressure alone
            # (the driver retries due arrivals) admits every event.
            # Named fleets: an unnamed det fleet would share the drill
            # fleet's label set and overwrite its final
            # fleet_replicas/fleet_healthy_replicas gauges before the
            # snapshot, so --require-autoscale would validate the wrong
            # fleet's wholeness.
            det_fleet = build_fleet(engine, max_replicas=a.max_replicas,
                                    compression=2.0 * a.compression,
                                    overload=False, name=f"det{run_idx}")
            det_driver = ReplayDriver(
                det_fleet, det_events, compression=2.0 * a.compression,
                max_wall_s=a.max_wall, tail_s=0.0,
            )
            runs.append(det_driver.run())
        r1, r2 = runs
        check(r1.lost == 0 and r2.lost == 0
              and r1.outcomes.get("shed", 0) == 0
              and r2.outcomes.get("shed", 0) == 0,
              "determinism runs: zero lost, zero shed (under-capacity)")
        check(set(r1.tokens) == set(r2.tokens)
              and len(r1.tokens) == len(det_events),
              f"same-seed re-run admitted the identical request set "
              f"({len(r1.tokens)} == {len(r2.tokens)} == "
              f"{len(det_events)})")
        check(r1.tokens == r2.tokens,
              "same-seed re-run produced the identical admitted-token set")

    # -- 7. retry-after honoring under deliberate overload --------------------
    # A fleet sized to lose: one replica the autoscaler cannot grow, a
    # queue a fraction of the drill's, and a depth-triggered shed ladder
    # with a short fuse. The burst MUST drive class sheds carrying
    # retry_after_s; the accounting question is what the driver does with
    # them (honor once, then record the retry's verdict).
    honored_before = reg.read_value("replay_retry_after_honored_total",
                                    component="replay")
    ov_cfg = trace_config(a.seed + 2, a.duration / 4.0, burst=True)
    ov_cfg = dataclasses.replace(
        ov_cfg, base_sessions_per_s=4.0, interactive_frac=0.5,
        session_max_turns=2, think_time_s=0.5,
    )
    ov_events = generate_trace(ov_cfg, PROMPTS)
    ov_fleet = ReplicaSet(
        engine,
        dataclasses.replace(SERVING, queue_capacity=6),
        settings=GREEDY,
        fleet=FleetConfig(replicas=1, fence_cooldown_s=0.3),
        resilience=RESILIENCE,
        integrity=IntegrityConfig(canary_max_tokens=8),
        name="ovreplay",
        overload=OverloadConfig(
            enabled=True, aging_s=1.0, deadline_admission=False,
            queue_frac_threshold=0.5, queue_window_s=0.3,
            healthy_window_s=0.3, eval_interval_s=0.02,
            burn_threshold=50.0,  # depth-driven: keep the trigger local
            retry_after_s=0.05,
        ),
        autoscale=AutoscaleConfig(enabled=True, min_replicas=1,
                                  max_replicas=1),
    )
    ov_report = ReplayDriver(
        ov_fleet, ov_events, compression=2.0 * a.compression,
        max_wall_s=a.max_wall, tail_s=0.5 * ov_cfg.duration_s,
    ).run()
    print("overload replay:", ov_report.summary())
    honored = reg.read_value("replay_retry_after_honored_total",
                             component="replay") - honored_before
    check(ov_report.gate_sheds >= 1,
          f"under-provisioned fleet shed at the gate "
          f"({ov_report.gate_sheds} gate sheds)")
    check(honored >= 1,
          f"driver honored retry_after_s on shed results "
          f"({honored:g} backoffs taken before re-offer)")
    check(not ov_report.timed_out and ov_report.lost == 0
          and ov_report.dropped == 0,
          f"overload replay ledger closed: zero accepted-then-lost, zero "
          f"dropped ({ov_report.accepted} accepted, "
          f"{ov_report.terminal} terminal)")

    # -- 8. snapshot ----------------------------------------------------------
    if a.telemetry_dir:
        path = T.write_snapshot(T.get_registry(), a.telemetry_dir)
        bad_snap = T.validate_snapshot(T.load_snapshot(path))
        check(not bad_snap, f"snapshot schema valid ({path})")
        if sink is not None:
            T.install_event_sink(None)
            sink.close()

    print(f"\nload replay drill: {'PASS' if not problems else 'FAIL'} "
          f"({len(problems)} problem(s))")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())

"""Validate a telemetry snapshot (schema + percentile self-consistency) and
assert the serving signals the ISSUE-3 acceptance criteria name are present
and nonzero.

Usage:
    python tools/validate_telemetry.py <telemetry-dir-or-snapshot.json>
    python tools/validate_telemetry.py <path> --require-serving
    python tools/validate_telemetry.py <path> --require-breaker
    python tools/validate_telemetry.py <path> --require-integrity
    python tools/validate_telemetry.py <path> --require-fleet
    python tools/validate_telemetry.py <path> --require-profile

Plain mode checks the schema only (`cli telemetry-report --validate` does
the same inline). ``--require-serving`` additionally requires nonzero TTFT,
queue-wait, and per-output-token histograms with p50 <= p95 <= p99 <= max —
the CI smoke step's gate after a ``--continuous --telemetry-dir`` run of the
tiny CPU study. ``--require-breaker`` requires the resilience signals the
chaos smoke step produces: breaker_state gauges, a full
closed->open->half-open->closed transition cycle, and a counted hang.
``--require-integrity`` requires the silent-corruption signals the extended
chaos drill produces: a counted NumericsFault, a manifest digest failure,
and a canary run with at least one mismatch. ``--require-fleet`` requires
the replica-failover signals the fleet drill produces: a nonzero
``fleet_fenced_total``, ``fleet_migrated_requests_total`` equal to
``fleet_migrated_recovered_total`` (every migrated request reached a
terminal Result), and ``fleet_healthy_replicas`` back to
``fleet_replicas`` (the killed replica rejoined via its canary probe).
``--require-profile`` requires the performance-attribution signals
(ISSUE 7): nonzero compile events (``compiles_total``), a populated
``achieved_over_achievable`` roofline gauge, a nonzero ``step_gap_s``
histogram, and a schema-valid ``trace.json`` beside the snapshot
containing prefill + decode spans and request lanes.
``--require-overload`` requires the overload-control signals the brownout
drill produces (ISSUE 8): nonzero ``shed_total``, an
``overload_transitions_total`` escalation AND a return to level 0, and
every ``overload_level`` gauge ending at 0.
``--require-prefix-cache`` requires the paged-KV prefix-reuse signals a
``--paged-kv --continuous`` study produces (ISSUE 10): nonzero
``prefix_cache_hit_tokens_total``, a ``prefix_cache_hit_ratio`` gauge above
0.5 (the counterfactual sweep's near-duplicate prompts MUST mostly hit),
populated block-arena occupancy gauges, a nonzero ``matched_prefix_len``
histogram, and — when the serving canary ran — zero
``canary_mismatch_total`` (the canary decodes through the live paged
scheduler against a static-engine reference, so it IS the token-parity
witness for the paged path).
``--require-autoscale`` requires the elastic-fleet signals the replay
smoke drill produces (ISSUE 11): at least one
``autoscale_events_total{direction="up"}`` AND one ``direction="down"``
(a full elastic cycle), ``replay_accepted_total`` equal to
``replay_terminal_total`` (zero accepted-then-lost across the replay),
``fleet_migrated_requests_total`` equal to
``fleet_migrated_recovered_total``, and every fleet's
``fleet_healthy_replicas`` back to its ``fleet_replicas`` (the trace's
crashed replica rejoined; retired replicas left the gauge entirely).
``--require-costmodel`` requires the decode cost ledger (ISSUE 12):
every program counted in ``compiles_total`` must have published a nonzero
``cost_ledger_bytes`` gauge (the jaxpr-walked analytical bytes per
component), plus nonzero ``cost_wall_s_total`` accumulation so the
``perf-report`` gap decomposition is derivable from the snapshot. A
``*_fused`` program (ISSUE 14's fused multi-step dispatch) additionally
must show its own measured wall AND a nonzero ``cost_host_gap_s_total``
— the host-gap term fusion exists to shrink must be MEASURED, never
assumed; ``--require-profile`` likewise holds a fused program to roofline
gauges under its own label.
``--require-incidents`` requires the incident engine's evidence (ISSUE 13):
at least one complete postmortem bundle under ``<dir>/incidents`` (manifest
with a known class + cause, flight-recorder rings, decision trail, registry
snapshot, trace slice — no torn ``.partial`` leftovers), a nonzero
``decisions_total`` audit trail, and ``incident_bundles_total`` agreeing
with the bundles on disk. ``--forbid-incidents`` is the inverse gate for
fault-free runs: ZERO bundles — an incident bundle from a clean study is
itself a defect.
``--require-rollout`` requires the zero-downtime upgrade evidence the
rollout drill produces (ISSUE 20): at least one
``rollout_transitions_total{to="complete"}`` AND one ``{to="rolled_back"}``
(the clean upgrade and the gate-triggered abort both happened), every
``rollout_rollbacks_total`` entry carrying a NAMED gate cause, every
``rollout_state`` gauge terminal (never abandoned mid-wave), and fleet
migration counters balanced (``fleet_migrated_requests_total`` equal to
``fleet_migrated_recovered_total`` — rollback re-fencing lost nothing).
``--require-fairness`` requires the fairness-observability signals a
fault-free ``--fairness-obs --continuous`` study produces (ISSUE 9):
nonzero ``fairness_requests_total`` and ``fairness_pairs_joined_total``,
populated ``fairness_dp``/``fairness_if``/``fairness_exposure_ratio``
run-window gauges in [0, 1], each streaming gauge matching its
``fairness_offline_*`` counterpart to fp tolerance (the live-vs-offline
cross-check), ZERO ``fairness_pair_divergence_total``, and ZERO
``fairness_alerts_total`` — a fault-free run must be silent.
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from fairness_llm_tpu.telemetry import load_snapshot, validate_snapshot  # noqa: E402

REQUIRED_SERVING_HISTOGRAMS = ("ttft_s", "queue_wait_s", "per_output_token_s")


def check(path: str, require_serving: bool = False,
          require_breaker: bool = False,
          require_integrity: bool = False,
          require_fleet: bool = False,
          require_profile: bool = False,
          require_overload: bool = False,
          require_fairness: bool = False,
          require_prefix_cache: bool = False,
          require_autoscale: bool = False,
          require_costmodel: bool = False,
          require_incidents: bool = False,
          require_memory: bool = False,
          require_rollout: bool = False,
          forbid_incidents: bool = False) -> int:
    snap = load_snapshot(path)
    problems = list(validate_snapshot(snap))
    if require_incidents or forbid_incidents:
        problems.extend(_check_incidents(path, snap,
                                         require=require_incidents,
                                         forbid=forbid_incidents))
    if require_profile:
        problems.extend(_check_profile(path, snap))
    if require_costmodel:
        problems.extend(_check_costmodel(snap))
    if require_memory:
        problems.extend(_check_memory(snap))
    if require_rollout:
        problems.extend(_check_rollout(snap))
    if require_fairness:
        problems.extend(_check_fairness(snap))
    if require_autoscale:
        problems.extend(_check_autoscale(snap))
    if require_prefix_cache:
        problems.extend(_check_prefix_cache(snap))
    if require_overload:
        counters = snap.get("counters", [])

        def total(name):
            return sum(c["value"] for c in counters if c.get("name") == name)

        if not total("shed_total"):
            problems.append(
                "shed_total is zero (overload control never shed anything)"
            )
        trans = [c for c in counters
                 if c.get("name") == "overload_transitions_total"]
        if not any(c["value"] for c in trans
                   if c.get("labels", {}).get("to") not in (None, "0")):
            problems.append(
                "no overload transition to a nonzero level (the brownout "
                "ladder never escalated)"
            )
        if not any(c["value"] for c in trans
                   if c.get("labels", {}).get("to") == "0"):
            problems.append(
                "no overload transition back to level 0 (the controller "
                "never de-escalated)"
            )
        levels = [g for g in snap.get("gauges", [])
                  if g.get("name") == "overload_level"]
        if not levels:
            problems.append("no overload_level gauge (overload control "
                            "never armed)")
        for g in levels:
            if g["value"] != 0:
                problems.append(
                    f"overload_level {g.get('labels', {})} ended at "
                    f"{g['value']:g} (controller did not return to 0)"
                )
    if require_fleet:
        counters = snap.get("counters", [])

        def total(name):
            return sum(c["value"] for c in counters if c.get("name") == name)

        fenced = total("fleet_fenced_total")
        if not fenced:
            problems.append(
                "fleet_fenced_total is zero (no replica was ever fenced)"
            )
        migrated = total("fleet_migrated_requests_total")
        recovered = total("fleet_migrated_recovered_total")
        if not migrated:
            problems.append(
                "fleet_migrated_requests_total is zero (failover never "
                "migrated anything)"
            )
        elif migrated != recovered:
            problems.append(
                f"migrated ({migrated}) != recovered ({recovered}) — "
                "migrated requests were lost"
            )
        # Pair healthy/replicas gauges per LABEL SET: a process can run
        # more than one fleet (one per sampler tuple, each with its own
        # {"fleet": name} label), and flattening by name would let one
        # whole fleet mask another's fenced-forever replica.
        fleets = {}
        for g in snap.get("gauges", []):
            labels = g.get("labels", {})
            if labels.get("component") != "fleet":
                continue
            key = tuple(sorted(
                (k, v) for k, v in labels.items() if k != "component"
            ))
            fleets.setdefault(key, {})[g["name"]] = g["value"]
        sized = {k: v for k, v in fleets.items()
                 if v.get("fleet_replicas", 0) >= 2}
        if not sized:
            problems.append(
                "no fleet_replicas gauge >= 2 (no fleet was armed)"
            )
        for key, vals in sized.items():
            replicas = vals["fleet_replicas"]
            healthy = vals.get("fleet_healthy_replicas", -1)
            if healthy != replicas:
                tag = dict(key).get("fleet", "default")
                problems.append(
                    f"fleet {tag!r}: fleet_healthy_replicas ({healthy}) != "
                    f"fleet_replicas ({replicas}) — a fenced replica never "
                    "rejoined"
                )
    if require_integrity:
        counters = snap.get("counters", [])

        def total(name):
            return sum(c["value"] for c in counters if c.get("name") == name)

        for name in ("numerics_faults_total", "manifest_failures_total",
                     "canary_runs_total", "canary_mismatch_total"):
            if not total(name):
                problems.append(
                    f"{name} is zero (integrity drill didn't exercise it)"
                )
    if require_breaker:
        gauges = [g for g in snap.get("gauges", [])
                  if g.get("name") == "breaker_state"]
        if not gauges:
            problems.append("no breaker_state gauges (resilience not armed?)")
        trans = {
            (c["labels"].get("stage"), c["labels"].get("to")): c["value"]
            for c in snap.get("counters", [])
            if c.get("name") == "breaker_transitions_total"
        }
        for to in ("open", "half_open", "closed"):
            if not any(v for (stage, t), v in trans.items() if t == to):
                problems.append(
                    f"no breaker transition to={to} (cycle incomplete)"
                )
        hangs = [c for c in snap.get("counters", [])
                 if c.get("name") == "watchdog_hangs_total" and c["value"]]
        if not hangs:
            problems.append("watchdog_hangs_total is zero (no hang counted)")
    if require_serving:
        hists = {
            h["name"]: h
            for h in snap.get("histograms", [])
            if h.get("labels", {}).get("component") == "serving"
        }
        for name in REQUIRED_SERVING_HISTOGRAMS:
            h = hists.get(name)
            if h is None:
                problems.append(f"serving histogram {name!r} missing")
            elif not h.get("count"):
                problems.append(f"serving histogram {name!r} is empty")
            elif not (h.get("min") or 0) > 0:
                problems.append(f"serving histogram {name!r} has zero samples")
        # validate_snapshot already enforced p50 <= p95 <= p99 <= max for
        # every non-empty histogram; nothing extra to re-derive here.
    if problems:
        print(f"INVALID: {path}")
        for p in problems:
            print(f"  - {p}")
        return 1
    print(f"OK: {path} "
          f"({len(snap.get('counters', []))} counters, "
          f"{len(snap.get('histograms', []))} histograms)")
    return 0


def _check_incidents(path: str, snap: dict, require: bool,
                     forbid: bool) -> list:
    """The --require-incidents / --forbid-incidents gates (ISSUE 13).
    Bundle shape is validated by the telemetry layer itself
    (``validate_incidents``); this adds the snapshot cross-checks — a
    recorded decision trail and counter/bundle agreement."""
    from fairness_llm_tpu.telemetry import validate_incidents
    from fairness_llm_tpu.telemetry.incidents import (
        INCIDENTS_DIRNAME,
        list_bundles,
    )

    tel_dir = path if os.path.isdir(path) else os.path.dirname(path)
    problems = list(validate_incidents(tel_dir, require=require,
                                       forbid=forbid))
    counters = snap.get("counters", [])

    def total(name):
        return sum(c["value"] for c in counters if c.get("name") == name)

    if forbid:
        # Disk state alone can't see a trigger whose dump FAILED (the
        # contained-exception path cleans its .partial): the snapshot
        # counter can. Any counted trigger in a must-be-clean run is a
        # violation, bundle or no bundle. (The counter only increments
        # while the engine is armed, so an unarmed drill stays clean.)
        fired = total("incident_triggers_total")
        if fired:
            problems.append(
                f"incident_triggers_total = {fired:g} in a run that must "
                "produce no incidents (a trigger fired — even if its "
                "bundle dump failed)"
            )
    if not require:
        return problems
    if not total("decisions_total"):
        problems.append("decisions_total is zero (the decision audit trail "
                        "never recorded — recording switched off?)")
    n_bundles = len(list_bundles(os.path.join(tel_dir, INCIDENTS_DIRNAME)))
    counted = total("incident_bundles_total")
    if n_bundles and counted != n_bundles:
        problems.append(
            f"incident_bundles_total ({counted:g}) != bundles on disk "
            f"({n_bundles}) — bundles from another run, or a dump the "
            "counter missed"
        )
    return problems


def _check_costmodel(snap: dict) -> list:
    """The --require-costmodel gate (ISSUE 12): every compiled program seen
    in ``compiles_total`` published a nonzero jaxpr-walked cost ledger, and
    the gap-attribution accumulators the ``perf-report`` decomposition
    needs (measured wall + per-component floor) are populated."""
    problems = []
    compiled = sorted({
        c.get("labels", {}).get("program")
        for c in snap.get("counters", [])
        if c.get("name") == "compiles_total" and c.get("value")
    } - {None})
    if not compiled:
        problems.append("compiles_total is empty (no compiled program to "
                        "require a ledger for)")
    ledgered = {}
    for g in snap.get("gauges", []):
        if g.get("name") != "cost_ledger_bytes":
            continue
        prog = g.get("labels", {}).get("program")
        ledgered[prog] = ledgered.get(prog, 0.0) + float(g.get("value", 0.0))
    for prog in compiled:
        if ledgered.get(prog, 0.0) <= 0:
            problems.append(
                f"compiled program {prog!r} has no nonzero cost_ledger_bytes "
                "gauge (the jaxpr cost walk never ran for it)"
            )
    walls = {
        g.get("labels", {}).get("program"): float(g.get("value", 0.0))
        for g in snap.get("gauges", [])
        if g.get("name") == "cost_wall_s_total"
    }
    if not any(v > 0 for v in walls.values()):
        problems.append("no nonzero cost_wall_s_total gauge (gap "
                        "attribution has no measured wall to decompose)")
    floors = [g for g in snap.get("gauges", [])
              if g.get("name") == "cost_component_min_s_total"
              and g.get("value", 0.0) > 0]
    if not floors:
        problems.append("cost_component_min_s_total is empty (no invocation "
                        "ever folded its ledger into the floor)")
    # Fused dispatch programs (ISSUE 14, runtime/stepbuilder.py): a
    # *_fused program in compiles_total publishes under its OWN label, so
    # beyond the every-program ledger check above it must show a measured
    # wall and a nonzero measured host gap — a fused program whose whole
    # point is host-gap amortization that never accumulated one means the
    # dispatch boundary instrumentation is broken, not that gaps are zero
    # (the between-dispatch eviction/admission work is never literally 0s).
    host_gaps = {
        g.get("labels", {}).get("program"): float(g.get("value", 0.0))
        for g in snap.get("gauges", [])
        if g.get("name") == "cost_host_gap_s_total"
    }
    for prog in compiled:
        # Mesh-sharded programs carry a @tp<k> label suffix
        # (runtime/stepbuilder.program_label); strip it before the
        # *_fused structural check so fused sharded programs are held to
        # the same contract as their single-device twins.
        if not prog.split("@", 1)[0].endswith("_fused"):
            continue
        if walls.get(prog, 0.0) <= 0:
            problems.append(
                f"fused program {prog!r} has no measured cost_wall_s_total "
                "(its invocations were never accumulated)"
            )
        if host_gaps.get(prog, 0.0) <= 0:
            problems.append(
                f"fused program {prog!r} has no nonzero "
                "cost_host_gap_s_total (the fused-dispatch boundary never "
                "measured a host gap)"
            )
    # Tensor-parallel programs (the stepbuilder's mesh axis): a @tp<k>
    # program runs real collectives, so its ledger must carry a nonzero
    # `collectives` component — a sharded run whose ledger shows none
    # means the collectives attribution (jaxpr prims, xplane regexes, or
    # the analytic GSPMD rows) silently fell through.
    coll = {}
    for g in snap.get("gauges", []):
        if (g.get("name") == "cost_ledger_bytes"
                and g.get("labels", {}).get("component") == "collectives"):
            prog = g.get("labels", {}).get("program")
            coll[prog] = coll.get(prog, 0.0) + float(g.get("value", 0.0))
    for prog in compiled:
        if "@tp" not in prog:
            continue
        if coll.get(prog, 0.0) <= 0:
            problems.append(
                f"sharded program {prog!r} has no nonzero collectives "
                "component in cost_ledger_bytes (tensor-parallel comm "
                "never attributed)"
            )
    return problems


def _check_rollout(snap: dict) -> list:
    """The --require-rollout gate (ISSUE 20): the rollout drill completed
    one upgrade AND rolled at least one back through a named gate, every
    rollout reached a terminal state, and migration accounting balanced
    (no request lost crossing a fenced new-version replica)."""
    problems = []
    counters = snap.get("counters", [])

    def total(name, **want):
        return sum(
            c["value"] for c in counters if c.get("name") == name
            and all(c.get("labels", {}).get(k) == v
                    for k, v in want.items())
        )

    if not total("rollout_transitions_total", to="complete"):
        problems.append(
            "no rollout reached complete (the clean-upgrade half of the "
            "drill never finished a wave sequence)"
        )
    if not total("rollout_transitions_total", to="rolled_back"):
        problems.append(
            "no rollout ever rolled back (the gate half of the drill "
            "never fired)"
        )
    causes = {
        c.get("labels", {}).get("cause")
        for c in counters
        if c.get("name") == "rollout_rollbacks_total" and c["value"] > 0
    }
    if not causes or None in causes:
        problems.append(
            "rollout_rollbacks_total carries no named gate cause (a "
            "rollback must say WHICH deployment gate fired)"
        )
    # Terminal-state proof: every fleet's rollout_state gauge must end in
    # rolled_back or complete — a mid-wave state in the final snapshot
    # means a rollout was abandoned, not resolved.
    terminal = {6.0, 7.0}  # ROLLOUT_STATES indices: rolled_back, complete
    for g in snap.get("gauges", []):
        if g.get("name") == "rollout_state" \
                and g.get("value") not in terminal:
            problems.append(
                f"rollout_state {g.get('labels', {})} ended mid-wave "
                f"(value {g.get('value')}) — rollout neither completed "
                "nor rolled back"
            )
    migrated = total("fleet_migrated_requests_total")
    recovered = total("fleet_migrated_recovered_total")
    if migrated != recovered:
        problems.append(
            f"fleet migration accounting unbalanced across the rollout "
            f"({migrated:g} migrated != {recovered:g} recovered)"
        )
    return problems


def _check_memory(snap: dict) -> list:
    """The --require-memory gate (ISSUE 18): the HBM memory ledger
    accounted real pools, every compiled program seen in ``compiles_total``
    (including ``*_fused`` and ``@tpN``) published its AOT
    ``program_memory_bytes``, and where a device limit exists the ledger
    total respects it."""
    problems = []
    gauges = snap.get("gauges", [])
    # Pool residency: at least the params pool plus one KV pool must be
    # nonzero — a serving run that allocated neither accounted nothing.
    pool_bytes = {}
    for g in gauges:
        lb = g.get("labels", {})
        if g.get("name") != "hbm_bytes" or "shard" in lb:
            continue
        pool = lb.get("pool")
        pool_bytes[pool] = pool_bytes.get(pool, 0.0) + float(
            g.get("value", 0.0))
    if pool_bytes.get("params", 0.0) <= 0:
        problems.append("hbm_bytes{pool=params} is zero or absent (no "
                        "engine ever registered its param tree)")
    if (pool_bytes.get("kv_contiguous", 0.0) <= 0
            and pool_bytes.get("kv_paged", 0.0) <= 0):
        problems.append("neither hbm_bytes{pool=kv_contiguous} nor "
                        "{pool=kv_paged} is nonzero (no scheduler ever "
                        "registered its KV state)")
    # Ledger total vs limit: where a limit exists (device-reported or
    # injected analytic), the accounted total must fit under it.
    total = sum(float(g.get("value", 0.0)) for g in gauges
                if g.get("name") == "hbm_bytes_total")
    limit = sum(float(g.get("value", 0.0)) for g in gauges
                if g.get("name") == "hbm_bytes_limit")
    if total <= 0:
        problems.append("hbm_bytes_total is zero or absent (the ledger "
                        "never reconciled)")
    if limit > 0 and total > limit:
        problems.append(
            f"ledger total {total:.0f} B exceeds the HBM limit "
            f"{limit:.0f} B (the accounting claims more memory than the "
            "device has)"
        )
    # Per-program AOT memory: every program compiled this run must have
    # published its memory_analysis — same every-program contract as the
    # cost ledger, and the @tpN / *_fused labels get no exemption (each
    # label IS its own compiled program).
    compiled = sorted({
        c.get("labels", {}).get("program")
        for c in snap.get("counters", [])
        if c.get("name") == "compiles_total" and c.get("value")
    } - {None})
    if not compiled:
        problems.append("compiles_total is empty (no compiled program to "
                        "require AOT memory analysis for)")
    prog_kinds = {}
    for g in gauges:
        if g.get("name") != "program_memory_bytes":
            continue
        lb = g.get("labels", {})
        prog_kinds.setdefault(lb.get("program"), set()).add(lb.get("kind"))
    for prog in compiled:
        kinds = prog_kinds.get(prog, set())
        missing = {"argument", "output", "temp"} - kinds
        if missing:
            problems.append(
                f"compiled program {prog!r} missing program_memory_bytes "
                f"kinds {sorted(missing)} (AOT memory_analysis never "
                "captured for it)"
            )
    return problems


def _check_autoscale(snap: dict) -> list:
    """The --require-autoscale gate (ISSUE 11): a full elastic cycle
    (scale-up AND scale-down), zero accepted-then-lost across the replay,
    migrated == recovered, and every fleet whole at the end."""
    problems = []
    counters = snap.get("counters", [])

    def total(name, **want):
        return sum(
            c["value"] for c in counters
            if c.get("name") == name and all(
                c.get("labels", {}).get(k) == v for k, v in want.items()
            )
        )

    ups = total("autoscale_events_total", direction="up")
    downs = total("autoscale_events_total", direction="down")
    if not ups:
        problems.append("no autoscale_events_total{direction=up} (the "
                        "burst never drove a scale-up)")
    if not downs:
        problems.append("no autoscale_events_total{direction=down} (the "
                        "quiet tail never drove a scale-down)")
    accepted = total("replay_accepted_total")
    terminal = total("replay_terminal_total")
    if not accepted:
        problems.append("replay_accepted_total is zero (no replay ran)")
    elif accepted != terminal:
        problems.append(
            f"replay accepted ({accepted:g}) != terminal ({terminal:g}) — "
            "accepted requests were lost"
        )
    migrated = total("fleet_migrated_requests_total")
    recovered = total("fleet_migrated_recovered_total")
    if migrated != recovered:
        problems.append(
            f"migrated ({migrated:g}) != recovered ({recovered:g}) — "
            "migrated requests were lost"
        )
    # Final fleet wholeness, per label set (same pairing rule as
    # --require-fleet; a retired replica shrinks fleet_replicas, so a
    # scaled-down fleet still reads whole here).
    fleets = {}
    for g in snap.get("gauges", []):
        labels = g.get("labels", {})
        if labels.get("component") != "fleet":
            continue
        key = tuple(sorted(
            (k, v) for k, v in labels.items() if k != "component"
        ))
        fleets.setdefault(key, {})[g["name"]] = g["value"]
    saw_fleet = False
    for key, vals in fleets.items():
        if "fleet_replicas" not in vals:
            continue
        saw_fleet = True
        replicas = vals["fleet_replicas"]
        healthy = vals.get("fleet_healthy_replicas", -1)
        if healthy != replicas:
            tag = dict(key).get("fleet", "default")
            problems.append(
                f"fleet {tag!r}: fleet_healthy_replicas ({healthy:g}) != "
                f"fleet_replicas ({replicas:g}) — the final fleet is not "
                "healthy"
            )
    if not saw_fleet:
        problems.append("no fleet_replicas gauge (no fleet was armed)")
    return problems


# |live - offline| bound for the streaming-vs-offline fairness cross-check:
# identical kernels over identically-valued count matrices, differing only
# in float summation order (vocab interning order) and float32-vs-float64
# mean accumulation — observed deltas are ~1e-7; 1e-4 leaves margin without
# letting a real aggregation bug (wrong group, dropped list) through.
FAIRNESS_TOLERANCE = 1e-4


def _check_fairness(snap: dict) -> list:
    """The --require-fairness gate (ISSUE 9): streaming group metrics
    populated and matching the offline scores, pair watch joined, and a
    fault-free run SILENT (zero divergence, zero neutrality alerts)."""
    problems = []
    counters = snap.get("counters", [])
    gauges = snap.get("gauges", [])

    def total(name):
        return sum(c["value"] for c in counters if c.get("name") == name)

    def gauge_rows(name, **want):
        out = []
        for g in gauges:
            lb = g.get("labels", {})
            if g.get("name") == name and all(lb.get(k) == v
                                             for k, v in want.items()):
                out.append(g)
        return out

    if not total("fairness_requests_total"):
        problems.append("fairness_requests_total is zero (no tagged "
                        "request ever finished — was --fairness-obs on?)")
    if not total("fairness_pairs_joined_total"):
        problems.append("fairness_pairs_joined_total is zero (the pair "
                        "watch never joined a counterfactual pair)")
    for name in ("fairness_dp", "fairness_if", "fairness_exposure_ratio"):
        rows = gauge_rows(name, window="run")
        if not rows:
            problems.append(f"no run-window {name} gauge (streaming "
                            "accumulators never refreshed)")
        for g in rows:
            if not 0.0 <= g["value"] <= 1.0:
                problems.append(f"{name} {g.get('labels', {})} = "
                                f"{g['value']:g} outside [0, 1]")
    # Live-vs-offline cross-check: every published offline reference must
    # have a streaming counterpart within tolerance.
    offline_of = {"fairness_offline_dp": "fairness_dp",
                  "fairness_offline_if": "fairness_if",
                  "fairness_offline_exposure": "fairness_exposure_ratio"}
    checked = 0
    for off_name, live_name in offline_of.items():
        for off in gauge_rows(off_name):
            attr = off.get("labels", {}).get("attribute")
            live = gauge_rows(live_name, attribute=attr, window="run")
            if not live:
                problems.append(f"{off_name}{{attribute={attr}}} has no "
                                f"run-window {live_name} counterpart")
                continue
            checked += 1
            delta = abs(live[0]["value"] - off["value"])
            if delta > FAIRNESS_TOLERANCE:
                problems.append(
                    f"{live_name}{{attribute={attr}}} = "
                    f"{live[0]['value']:.6f} vs offline {off['value']:.6f} "
                    f"(|delta| {delta:.2e} > {FAIRNESS_TOLERANCE:g}) — "
                    "streaming accumulation diverged from the batch metric"
                )
    if not checked:
        problems.append("no fairness_offline_* gauges (the phase never "
                        "published its offline reference scores)")
    # A fault-free run must be SILENT: serving treated every group equally
    # and no pair's delivery was impaired.
    if total("fairness_pair_divergence_total"):
        problems.append("fairness_pair_divergence_total is nonzero in a "
                        "fault-free run (serving impaired a pair member)")
    if total("fairness_alerts_total"):
        problems.append("fairness_alerts_total is nonzero in a fault-free "
                        "run (the neutrality audit saw group disparity)")
    return problems


def _check_prefix_cache(snap: dict) -> list:
    """The --require-prefix-cache gate (ISSUE 10): the paged KV cache hit,
    the hit RATIO cleared 0.5 on the counterfactual study, the block arena
    reported its occupancy, and the canary (when armed) saw zero
    mismatches — parity-clean prefix reuse, not just nonzero counters."""
    problems = []
    counters = snap.get("counters", [])
    gauges = snap.get("gauges", [])

    def total(name):
        return sum(c["value"] for c in counters if c.get("name") == name)

    hit = total("prefix_cache_hit_tokens_total")
    miss = total("prefix_cache_miss_tokens_total")
    if not hit:
        problems.append(
            "prefix_cache_hit_tokens_total is zero (the radix index never "
            "matched a prefix — was --paged-kv on?)"
        )
    elif hit + miss and hit / (hit + miss) <= 0.5:
        problems.append(
            f"prefix-cache hit ratio {hit / (hit + miss):.3f} <= 0.5 over "
            f"{hit + miss} prompt tokens (the counterfactual sweep's "
            "near-duplicate prompts should mostly hit)"
        )
    ratios = [g for g in gauges if g.get("name") == "prefix_cache_hit_ratio"]
    if not ratios:
        problems.append("no prefix_cache_hit_ratio gauge (paged KV never "
                        "published its live ratio)")
    occ = [g for g in gauges if g.get("name") == "kv_block_occupancy"]
    if not occ:
        problems.append("no kv_block_occupancy gauge (block arena "
                        "accounting never published)")
    matched = [h for h in snap.get("histograms", [])
               if h.get("name") == "matched_prefix_len"]
    if not any(h.get("count") for h in matched):
        problems.append("matched_prefix_len histogram empty (no paged "
                        "prefill recorded its match)")
    runs = total("canary_runs_total")
    mismatches = total("canary_mismatch_total")
    if runs and mismatches:
        problems.append(
            f"canary_mismatch_total = {mismatches:g} with --paged-kv (the "
            "paged scheduler decoded DIFFERENT tokens than the static "
            "reference — prefix reuse broke parity)"
        )
    return problems


def _check_profile(path: str, snap: dict) -> list:
    """The --require-profile gate: compile events, roofline gauges, step
    gaps, and a schema-valid trace.json with the span kinds the ISSUE-7
    acceptance names (prefill/decode/request on tracks)."""
    import json

    from fairness_llm_tpu.telemetry import TRACE_FILENAME, validate_chrome_trace

    problems = []
    if not sum(c["value"] for c in snap.get("counters", [])
               if c.get("name") == "compiles_total"):
        problems.append("compiles_total is zero (no compile event recorded)")
    aoa = [g for g in snap.get("gauges", [])
           if g.get("name") == "achieved_over_achievable"]
    if not aoa:
        problems.append("no achieved_over_achievable gauge (roofline "
                        "accounting never ran)")
    elif not any(g["value"] > 0 for g in aoa):
        problems.append("achieved_over_achievable is zero everywhere")
    gaps = [h for h in snap.get("histograms", [])
            if h.get("name") == "step_gap_s"]
    if not any(h.get("count") for h in gaps):
        problems.append("step_gap_s histogram empty (no consecutive decode "
                        "chunks recorded)")
    # A fused step program (ISSUE 14) must publish roofline gauges under
    # its OWN label — fused chunks dividing by actual fused steps is the
    # per-iteration correctness the satellite pins, and a fused program
    # silently folding into the unfused label would hide it.
    fused = sorted({
        c.get("labels", {}).get("program")
        for c in snap.get("counters", [])
        if c.get("name") == "compiles_total" and c.get("value")
        and str(c.get("labels", {}).get("program", ""))
        .split("@", 1)[0].endswith("_fused")  # @tp<k> mesh suffix strips off
    })
    for prog in fused:
        if not any(g.get("labels", {}).get("program") == prog
                   and g["value"] > 0 for g in aoa):
            problems.append(
                f"fused program {prog!r} has no nonzero "
                "achieved_over_achievable gauge (fused chunks must feed "
                "the roofline under their own label)"
            )
    trace_dir = path if os.path.isdir(path) else os.path.dirname(path)
    trace_path = os.path.join(trace_dir, TRACE_FILENAME)
    if not os.path.exists(trace_path):
        problems.append(f"{trace_path} missing (run with --trace-out or "
                        "--telemetry-dir)")
        return problems
    with open(trace_path, encoding="utf-8") as f:
        trace = json.load(f)
    problems.extend(f"trace.json: {p}" for p in validate_chrome_trace(trace))
    cats = {ev.get("cat") for ev in trace.get("traceEvents", [])
            if ev.get("ph") == "X"}
    for want in ("prefill", "decode"):
        if want not in cats:
            problems.append(f"trace.json has no cat={want!r} spans")
    if not any(ev.get("ph") == "b" for ev in trace.get("traceEvents", [])):
        problems.append("trace.json has no request spans (async b/e events)")
    return problems


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("path")
    ap.add_argument("--require-serving", action="store_true")
    ap.add_argument("--require-breaker", action="store_true")
    ap.add_argument("--require-integrity", action="store_true")
    ap.add_argument("--require-fleet", action="store_true")
    ap.add_argument("--require-profile", action="store_true")
    ap.add_argument("--require-overload", action="store_true")
    ap.add_argument("--require-fairness", action="store_true")
    ap.add_argument("--require-prefix-cache", action="store_true")
    ap.add_argument("--require-autoscale", action="store_true")
    ap.add_argument("--require-costmodel", action="store_true")
    ap.add_argument("--require-incidents", action="store_true")
    ap.add_argument("--require-memory", action="store_true")
    ap.add_argument("--require-rollout", action="store_true")
    ap.add_argument("--forbid-incidents", action="store_true")
    a = ap.parse_args()
    return check(a.path, require_serving=a.require_serving,
                 require_breaker=a.require_breaker,
                 require_integrity=a.require_integrity,
                 require_fleet=a.require_fleet,
                 require_profile=a.require_profile,
                 require_overload=a.require_overload,
                 require_fairness=a.require_fairness,
                 require_prefix_cache=a.require_prefix_cache,
                 require_autoscale=a.require_autoscale,
                 require_costmodel=a.require_costmodel,
                 require_incidents=a.require_incidents,
                 require_memory=a.require_memory,
                 require_rollout=a.require_rollout,
                 forbid_incidents=a.forbid_incidents)


if __name__ == "__main__":
    sys.exit(main())

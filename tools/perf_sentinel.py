"""Noise-aware perf regression sentinel: compare a fresh bench run against
the committed ``bench_baseline.json``.

The CPU harness's single-run wall jitter is ±30-60% (docs/PERFORMANCE.md
methodology), so naive "wall got slower" gates would flap. This sentinel is
noise-aware by construction:

- **Fingerprint refusal** — a baseline records its harness fingerprint
  (jax version, platform, device kind, cpu count, model —
  ``bench.harness_fingerprint``). Comparing numbers across fingerprints is
  meaningless, so the sentinel REFUSES (exit 2) instead of passing or
  failing; ``--allow-refusal`` downgrades the refusal to a reported skip
  (exit 0) for CI runners whose hardware can never match the committed
  baseline's.
- **Best-of-N measurement** — ``--run`` executes the cheap bench entries N
  times (``--reps``) in subprocesses and keeps each wall entry's BEST
  value (rates: max), the same min-of-reps idiom every bench entry uses
  internally. ``exact`` entries must agree across reps — disagreement IS
  the regression (nondeterminism), reported as parity drift.
- **Ratio bands for walls** — a ``wall`` entry regresses only when
  fresh/baseline leaves ``[1/band, band]`` (default 2.0x: wide enough for
  the harness's known jitter after best-of-N, tight enough that an
  injected 3x slowdown always fails). ``--wall-warn-only`` downgrades wall
  violations to warnings (the CI mode) — parity stays hard.
- **Exact comparison for counters** — hit ratios, token checksums/counts,
  shed rates (``kind: exact``) are deterministic on one fingerprint and
  compared exactly; drift there is a correctness regression, never noise.

Usage:
    python tools/perf_sentinel.py --baseline bench_baseline.json --fresh fresh.json
    python tools/perf_sentinel.py --baseline bench_baseline.json \
        --run --reps 2 --entries continuous,prefix_cache [--wall-warn-only]
    python tools/perf_sentinel.py --self-check bench_baseline.json

``--self-check`` proves the gates bite on THIS harness without needing a
matching committed fingerprint: a clean self-comparison must pass, an
injected 3x slowdown must fail, an injected parity drift must fail, and a
perturbed fingerprint must refuse — the CI step hard-fails if any gate
fails to bite. Exit codes: 0 ok / warn-only, 1 regression, 2 refused.
"""

from __future__ import annotations

import argparse
import copy
import json
import os
import subprocess
import sys
import tempfile
from typing import Dict, List, Optional, Tuple

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

DEFAULT_WALL_BAND = 2.0
# Entries cheap enough for a CI runner (the headline sweep always rides).
CHEAP_ENTRIES = "continuous,prefix_cache"

EXIT_OK = 0
EXIT_REGRESSION = 1
EXIT_REFUSED = 2


def load_baseline(path: str) -> Dict:
    with open(path, encoding="utf-8") as f:
        base = json.load(f)
    for key in ("fingerprint", "entries"):
        if key not in base:
            raise SystemExit(f"{path}: not a bench baseline (missing {key!r})")
    return base


def fingerprint_mismatches(a: Dict, b: Dict) -> List[str]:
    """Human-readable field-by-field fingerprint differences (empty =
    comparable)."""
    out = []
    for key in sorted(set(a) | set(b)):
        if a.get(key) != b.get(key):
            out.append(f"{key}: baseline={a.get(key)!r} fresh={b.get(key)!r}")
    return out


def compare(baseline: Dict, fresh: Dict,
            wall_band: float = DEFAULT_WALL_BAND) -> Tuple[List[str], List[str], Dict]:
    """Compare two baseline-format records (same fingerprint asserted by
    the caller). Returns (problems, wall_violations, report): wall
    violations are split out so the caller can decide whether they are
    hard failures or warnings; ``problems`` (missing entries, exact-entry
    drift) are ALWAYS hard."""
    problems: List[str] = []
    wall_violations: List[str] = []
    rows = {}
    for name, spec in baseline["entries"].items():
        kind = spec.get("kind", "exact")
        base_v = spec.get("value")
        fresh_spec = fresh["entries"].get(name)
        row = {"kind": kind, "baseline": base_v}
        if fresh_spec is None:
            problems.append(f"{name}: present in baseline, missing from the "
                            "fresh run (entry skipped or renamed?)")
            row["status"] = "missing"
            rows[name] = row
            continue
        fresh_v = fresh_spec.get("value")
        row["fresh"] = fresh_v
        if kind == "wall":
            try:
                ratio = float(fresh_v) / float(base_v)
            except (TypeError, ValueError, ZeroDivisionError):
                ratio = None
            row["ratio"] = round(ratio, 4) if ratio is not None else None
            if ratio is None or not (1.0 / wall_band <= ratio <= wall_band):
                wall_violations.append(
                    f"{name}: {base_v!r} -> {fresh_v!r} "
                    f"(ratio {ratio if ratio is None else round(ratio, 3)}; "
                    f"band [{1 / wall_band:.3f}, {wall_band:.3f}])"
                )
                row["status"] = "wall_violation"
            else:
                row["status"] = "ok"
        else:
            if fresh_v != base_v:
                problems.append(
                    f"{name}: exact-compared counter drifted "
                    f"({base_v!r} -> {fresh_v!r}) — correctness regression, "
                    "not noise"
                )
                row["status"] = "drift"
            else:
                row["status"] = "ok"
        rows[name] = row
    report = {
        "wall_band": wall_band,
        "entries": rows,
        "problems": problems,
        "wall_violations": wall_violations,
    }
    return problems, wall_violations, report


def merge_best(runs: List[Dict]) -> Tuple[Dict, List[str]]:
    """Best-of-N merge of baseline-format records: per wall entry keep the
    BEST rep in the entry's improvement direction (``better``: "higher"
    for rates/speedups — the default — "lower" for on/off overhead
    ratios); exact entries must agree across runs (disagreement = parity
    drift)."""
    problems: List[str] = []
    merged = copy.deepcopy(runs[0])
    for run in runs[1:]:
        for name, spec in run["entries"].items():
            have = merged["entries"].get(name)
            if have is None:
                merged["entries"][name] = spec
                continue
            if spec.get("kind") == "wall":
                lower = spec.get("better", "higher") == "lower"
                try:
                    v, cur = float(spec["value"]), float(have["value"])
                    if (v < cur) if lower else (v > cur):
                        have["value"] = spec["value"]
                except (TypeError, ValueError):
                    pass
            elif spec.get("value") != have.get("value"):
                problems.append(
                    f"{name}: exact entry disagrees BETWEEN reps of the "
                    f"fresh run ({have.get('value')!r} vs "
                    f"{spec.get('value')!r}) — nondeterministic harness"
                )
    return merged, problems


def run_bench(entries: str, reps: int) -> Tuple[Dict, List[str]]:
    """Run the cheap bench entries ``reps`` times in subprocesses; each run
    writes a baseline-format record via ``--baseline-out``."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    runs = []
    with tempfile.TemporaryDirectory(prefix="perf_sentinel_") as td:
        for rep in range(reps):
            out = os.path.join(td, f"run{rep}.json")
            cmd = [sys.executable, os.path.join(root, "bench.py"),
                   "--entries", entries, "--baseline-out", out]
            proc = subprocess.run(cmd, cwd=root, capture_output=True,
                                  text=True)
            if proc.returncode != 0:
                raise SystemExit(
                    f"bench rep {rep} failed (exit {proc.returncode}):\n"
                    f"{proc.stderr[-2000:]}"
                )
            with open(out, encoding="utf-8") as f:
                runs.append(json.load(f))
    return merge_best(runs)


def self_check(baseline_path: str) -> int:
    """Prove the gates bite on this harness: clean pass, 3x-slowdown fail,
    parity-drift fail, cross-fingerprint refusal."""
    base = load_baseline(baseline_path)
    failures = []

    # 1. A clean self-comparison must pass.
    problems, walls, _ = compare(base, base)
    if problems or walls:
        failures.append(f"clean self-comparison not clean: {problems + walls}")

    # 2. An injected 3x slowdown on every wall entry must violate the band.
    slow = copy.deepcopy(base)
    n_wall = 0
    for spec in slow["entries"].values():
        if spec.get("kind") == "wall":
            spec["value"] = float(spec["value"]) / 3.0
            n_wall += 1
    if n_wall:
        problems, walls, _ = compare(base, slow)
        if len(walls) != n_wall:
            failures.append(
                f"3x slowdown flagged {len(walls)}/{n_wall} wall entries"
            )
    else:
        failures.append("baseline has no wall entries to slow down")

    # 3. An injected token-parity drift must hard-fail.
    drift = copy.deepcopy(base)
    n_exact = 0
    for spec in drift["entries"].values():
        if spec.get("kind") == "exact":
            spec["value"] = "DRIFTED" if isinstance(spec["value"], str) \
                else (spec["value"] or 0) + 1
            n_exact += 1
    if n_exact:
        problems, _, _ = compare(base, drift)
        if len(problems) != n_exact:
            failures.append(
                f"parity drift flagged {len(problems)}/{n_exact} entries"
            )
    else:
        failures.append("baseline has no exact entries to drift")

    # 4. A perturbed fingerprint must refuse.
    other = dict(base["fingerprint"], cpu_count=-1)
    if not fingerprint_mismatches(base["fingerprint"], other):
        failures.append("perturbed fingerprint compared as identical")

    if failures:
        print("SELF-CHECK FAILED:")
        for f in failures:
            print(f"  - {f}")
        return EXIT_REGRESSION
    print(f"SELF-CHECK OK: clean pass / 3x-slowdown fail ({n_wall} wall "
          f"entries) / parity-drift fail ({n_exact} exact entries) / "
          "cross-fingerprint refusal all behave")
    return EXIT_OK


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--baseline", help="committed bench_baseline.json")
    ap.add_argument("--fresh", help="a fresh baseline-format record to "
                                    "compare (from bench --baseline-out)")
    ap.add_argument("--run", action="store_true",
                    help="measure fresh entries now: run bench.py "
                         "--entries ... N times, best-of-N merge")
    ap.add_argument("--reps", type=int, default=2,
                    help="with --run: best-of-N repetitions (default 2)")
    ap.add_argument("--entries", default=CHEAP_ENTRIES,
                    help="with --run: bench entries to measure "
                         f"(default: {CHEAP_ENTRIES})")
    ap.add_argument("--wall-band", type=float, default=DEFAULT_WALL_BAND,
                    help="wall-entry ratio band (default 2.0)")
    ap.add_argument("--wall-warn-only", action="store_true",
                    help="wall-band violations warn instead of failing "
                         "(parity/counter drift still hard-fails) — the "
                         "CI mode")
    ap.add_argument("--allow-refusal", action="store_true",
                    help="a fingerprint refusal exits 0 (reported, no "
                         "numbers compared) instead of 2 — for CI runners "
                         "whose hardware cannot match the committed "
                         "baseline's fingerprint")
    ap.add_argument("--report", help="write the comparison report JSON here")
    ap.add_argument("--self-check", metavar="BASELINE",
                    help="prove the gates bite on this harness, then exit")
    a = ap.parse_args()

    if a.self_check:
        return self_check(a.self_check)
    if not a.baseline or not (a.fresh or a.run):
        ap.error("need --baseline plus one of --fresh/--run "
                 "(or --self-check)")
    if a.wall_band <= 1.0:
        ap.error("--wall-band must be > 1")

    base = load_baseline(a.baseline)
    rep_problems: List[str] = []
    if a.fresh:
        fresh = load_baseline(a.fresh)
    else:
        fresh, rep_problems = run_bench(a.entries, max(a.reps, 1))

    report: Dict = {
        "baseline": a.baseline,
        "baseline_fingerprint": base["fingerprint"],
        "fresh_fingerprint": fresh["fingerprint"],
    }

    def write_report():
        if a.report:
            with open(a.report, "w", encoding="utf-8") as f:
                json.dump(report, f, indent=1, sort_keys=True)
            print(f"report: {a.report}")

    mism = fingerprint_mismatches(base["fingerprint"], fresh["fingerprint"])
    if mism:
        report["status"] = "refused"
        report["fingerprint_mismatches"] = mism
        print(f"REFUSED: baseline {a.baseline} was recorded under a "
              "different harness fingerprint — cross-fingerprint numbers "
              "are not comparable:")
        for m in mism:
            print(f"  - {m}")
        write_report()
        if a.allow_refusal:
            print("(--allow-refusal: exiting 0 without comparing)")
            return EXIT_OK
        return EXIT_REFUSED

    # Only the entries the fresh run actually measured are comparable when
    # it ran a subset (--entries): drop baseline entries outside it, BUT
    # only entry-name prefixes the subset explains — a wholesale drop would
    # let a silently-skipped headline pass.
    if a.run:
        measured = {e.strip() for e in a.entries.split(",") if e.strip()}
        measured.add("headline")
        base = copy.deepcopy(base)
        base["entries"] = {
            k: v for k, v in base["entries"].items()
            if k.split(".", 1)[0] in measured
        }

    problems, wall_violations, cmp_report = compare(
        base, fresh, wall_band=a.wall_band
    )
    problems = rep_problems + problems
    report.update(cmp_report)

    for w in wall_violations:
        tag = "WARN (wall band)" if a.wall_warn_only else "FAIL (wall band)"
        print(f"{tag}: {w}")
    for p in problems:
        print(f"FAIL: {p}")
    hard = list(problems) + ([] if a.wall_warn_only else wall_violations)
    report["status"] = "fail" if hard else (
        "warn" if wall_violations else "ok")
    write_report()
    if hard:
        print(f"PERF SENTINEL: {len(hard)} failure(s)")
        return EXIT_REGRESSION
    ok_n = sum(1 for r in report["entries"].values()
               if r.get("status") == "ok")
    print(f"PERF SENTINEL: OK ({ok_n} entries within bounds"
          + (f", {len(wall_violations)} wall warning(s)"
             if wall_violations else "") + ")")
    return EXIT_OK


if __name__ == "__main__":
    sys.exit(main())

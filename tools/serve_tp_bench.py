#!/usr/bin/env python
"""Real-mesh tensor-parallel serving benchmark (the bench's ``serve_tp``
entry).

Measures what the emulated 70B shard (``tools/measure_70b_shard.py``, the
``llama70b_shard`` entry) deliberately omits: decode over an ACTUAL tp mesh
with the collectives executed — every step program lowered as one SPMD
computation, params + slot KV cache + carried logits sharded, XLA-inserted
all-reduces on the wire. On the CPU harness the mesh is real too
(``--xla_force_host_platform_device_count``), so this runs in CI.

Must be a subprocess of bench.py / CI, never imported into a live jax
process: the forced host device count only takes effect when set BEFORE
jax initializes, which is why the env mutation sits above the imports.

Contract (asserted, not just reported):
  * token-for-token parity: tp=N serving — contiguous AND paged, fuse 1
    AND 4 — decodes exactly the single-device engine's greedy stream;
  * collectives executed: the compiled tp step program's HLO contains
    all-reduce (plus the cost ledger's nonzero ``collectives`` row under
    the ``@tp<N>`` program label).

Emits one JSON object on the last stdout line (bench.py parses it):
wall-clock tokens/sec per variant plus the exact token checksum the perf
sentinel compares byte-for-byte.
"""

import argparse
import hashlib
import json
import os
import sys
import time

ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
ap.add_argument("--tp", type=int, default=2)
ap.add_argument("--model", default="tiny-test")
ap.add_argument("--reps", type=int, default=3)
args = ap.parse_args()

if len(jax_flags := os.environ.get("XLA_FLAGS", "")) == 0 or \
        "host_platform_device_count" not in jax_flags:
    os.environ["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={args.tp} " + jax_flags)
os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

from fairness_llm_tpu.config import (  # noqa: E402
    MeshConfig,
    ModelSettings,
    ServingConfig,
)
from fairness_llm_tpu.models.configs import get_model_config  # noqa: E402
from fairness_llm_tpu.parallel import make_mesh  # noqa: E402
from fairness_llm_tpu.runtime.engine import DecodeEngine  # noqa: E402
from fairness_llm_tpu.serving import ContinuousScheduler, Request  # noqa: E402
from fairness_llm_tpu.telemetry import use_registry  # noqa: E402

M = 16  # decode budget per request
NUM_SLOTS = 4
GREEDY = ModelSettings(temperature=0.0, top_k=0, top_p=1.0, max_tokens=M)
PROMPTS = [
    "the cat sat on the mat",
    "a b c d e f g h",
    "one two three four five six seven",
    "to be or not to be that is the question",
    "pack my box with five dozen",
    "colorless green ideas sleep furiously now",
    "the quick brown fox jumps over",
    "never gonna give you up never",
]


def run(tp: int, model_name: str, reps: int) -> dict:
    if jax.device_count() % tp != 0:
        raise SystemExit(
            f"device count {jax.device_count()} not divisible by tp={tp}")
    cfg = get_model_config(model_name)
    out: dict = {"tp": tp, "model": model_name,
                 "devices": jax.device_count()}

    # Single-device greedy reference: the parity oracle AND the speed
    # baseline the tp variants are compared against.
    ref_engine = DecodeEngine(cfg, seed=0)
    ref = [ref_engine.generate([p], settings=GREEDY, max_new_tokens=M)
           for p in PROMPTS]
    ref_tokens = [tuple(int(t) for t in r.tokens[0]) for r in ref]
    out["token_checksum"] = hashlib.sha256(
        repr(ref_tokens).encode()).hexdigest()[:16]
    out["useful_tokens"] = sum(len(t) for t in ref_tokens)

    mesh = make_mesh(MeshConfig(tp=tp))
    collective_rows = {}
    for paged in (False, True):
        for fuse in (1, 4):
            tag = f"{'paged' if paged else 'contig'}_k{fuse}"
            engine = DecodeEngine(cfg, seed=0, mesh=mesh)
            with use_registry() as reg:
                sched = ContinuousScheduler(
                    engine,
                    ServingConfig(
                        enabled=True, num_slots=NUM_SLOTS, decode_chunk=4,
                        fuse_steps=fuse, max_new_tokens=M, paged_kv=paged,
                        tp=tp,
                    ),
                    settings=GREEDY,
                )

                def serve(rep):
                    reqs = [Request(prompt=p, id=f"{tag}_{rep}_{i}",
                                    settings=GREEDY)
                            for i, p in enumerate(PROMPTS)]
                    t0 = time.perf_counter()
                    results = sched.serve(reqs)
                    wall = time.perf_counter() - t0
                    toks = [tuple(int(t) for t in r.tokens)
                            for r in results]
                    assert all(r.ok for r in results), (tag, results)
                    return wall, toks

                serve("warm")  # compile outside the timed reps
                best = None
                for rep in range(reps):
                    wall, toks = serve(rep)
                    assert toks == ref_tokens, (
                        f"{tag}: tp={tp} token stream diverged from the "
                        f"single-device engine")
                    if best is None or wall < best:
                        best = wall
                # Collectives executed, not omitted: the ledger published
                # a nonzero collectives row under this tp program label.
                coll = sum(
                    inst.value for inst in reg.instruments()
                    if inst.name == "cost_ledger_bytes"
                    and inst.labels.get("component") == "collectives"
                    and f"@tp{tp}" in inst.labels.get("program", "")
                )
                assert coll > 0, f"{tag}: no collectives attributed"
                collective_rows[tag] = coll
            out[tag] = {
                "wall_s": round(best, 3),
                "tokens_per_sec": round(out["useful_tokens"] / best, 1),
            }

    # HLO witness: the sharded contiguous step program really contains
    # all-reduce ops (GSPMD inserted them post-partitioning, so the jaxpr
    # can't show them — the compiled module can).
    import flax.linen as nn

    from fairness_llm_tpu.parallel.sharding import make_axis_rules
    from fairness_llm_tpu.runtime.stepbuilder import build_serve_step
    from fairness_llm_tpu.runtime.sampling import SamplerSettings

    engine = DecodeEngine(cfg, seed=0, mesh=mesh)
    sched = ContinuousScheduler(
        engine, ServingConfig(enabled=True, num_slots=NUM_SLOTS,
                              decode_chunk=4, max_new_tokens=M, tp=tp),
        settings=GREEDY)
    step = build_serve_step(
        engine.config, engine.model, SamplerSettings(),
        engine.tokenizer.pad_id, engine.tokenizer.eos_id,
        num_slots=NUM_SLOTS, chunk=4, guard=False, paged=False, fuse=1,
    )
    import jax.numpy as jnp

    zeros = lambda *s, dt=jnp.int32: jnp.zeros(s, dt)  # noqa: E731
    with mesh, nn.logical_axis_rules(make_axis_rules(cfg, mesh)):
        lowered = jax.jit(step).lower(
            engine.params, sched._cache, sched._prev_logits,
            zeros(NUM_SLOTS), zeros(NUM_SLOTS), zeros(NUM_SLOTS),
            zeros(NUM_SLOTS), zeros(NUM_SLOTS, dt=jnp.bool_),
            zeros(NUM_SLOTS, dt=jnp.bool_),
        )
        hlo = lowered.compile().as_text()
    out["all_reduce_in_hlo"] = hlo.count("all-reduce")
    assert out["all_reduce_in_hlo"] > 0, \
        "tp step program compiled without any all-reduce"
    out["collective_ledger_bytes"] = collective_rows
    # The single-device reference walls are batch-1 static calls, not a
    # load-parity A/B, so only the serving-loop rates are reported;
    # cross-variant ratios are meaningful within this record.
    return out


if __name__ == "__main__":
    rec = run(args.tp, args.model, args.reps)
    print(json.dumps(rec))

"""Live per-chip measurement of the llama3-70b-int8 tp=8 DECODE workload.

No environment here has 8 chips, but tp=8 sharding makes each chip's decode
step a well-defined single-chip program: 1/8 of the heads/ff/vocab with the
FULL d_model (the replicated dim), int8 weights — ~8.9 GB/chip, exactly the
per-shard tree the AOT fit proof accounts. This runs that per-shard model
LIVE on one v5e chip with random int8 weights and measures the decode rate
the real tp=8 deployment would sustain per chip, modulo the psum latency
the single-chip program omits (two all-reduces per layer over ICI — ~us
scale against the ~18 ms weight-streaming step).

    python tools/measure_70b_shard.py [batch] [new_tokens]
"""

from __future__ import annotations

import dataclasses
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

TP = 8


def run(batch: int = 8, new_tokens: int = 32) -> dict:
    import jax

    from fairness_llm_tpu.config import ModelSettings
    from fairness_llm_tpu.models.configs import get_model_config
    from fairness_llm_tpu.runtime.engine import DecodeEngine

    full = get_model_config("llama3-70b-int8")
    shard = dataclasses.replace(
        full,
        name="llama3-70b-int8-shard8",
        num_heads=full.num_heads // TP,        # 8 q heads/chip
        num_kv_heads=full.num_kv_heads // TP,  # 1 kv head/chip
        d_ff=full.d_ff // TP,                  # 3584
        vocab_size=full.vocab_size // TP,      # 16032 (vocab-sharded lm_head)
        max_seq_len=2048,
    )
    eng = DecodeEngine(shard, seed=0)
    prompts = [f"profile {i}: user likes classic films and" for i in range(batch)]

    # The decode-step MARGINAL: time the same study at two decode lengths
    # and diff — a single wall/new_tokens division would smear the prefill
    # (at batch 48 the S=128 prefill is ~0.9 s of dense-FLOP work, which
    # once masqueraded as "the step got slower with batch").
    def timed(new):
        settings = ModelSettings(
            temperature=0.7, top_k=0, top_p=1.0, max_tokens=new
        )
        t0 = time.time()
        eng.generate(prompts, settings, seed=0)  # compile + warmup
        compile_s = time.time() - t0
        best = None
        for rep in range(2):
            t0 = time.perf_counter()
            out = eng.generate(prompts, settings, seed=rep + 1)
            jax.block_until_ready(out.tokens)
            wall = time.perf_counter() - t0
            best = wall if best is None else min(best, wall)
        return best, compile_s, out

    short = max(8, new_tokens // 4)
    wall_short, compile_a, _ = timed(short)
    wall_long, compile_b, out = timed(new_tokens)
    ms_step = (wall_long - wall_short) / (new_tokens - short) * 1e3

    # per-step bytes: the int8 layer kernels + bf16 embed/lm-head; embed is
    # gathered (not streamed); the quantized tree is the stream.
    from bench import decode_step_bytes

    step_bytes = decode_step_bytes(shard, out.stats)
    return {
        "model": shard.name,
        "emulates": "llama3-70b-int8 tp=8, per-chip shard (collectives omitted)",
        "batch": out.stats["batch"],
        "new_tokens": [short, new_tokens],
        "compile_plus_warmup_s": round(compile_a + compile_b, 1),
        "walls_s": [round(wall_short, 3), round(wall_long, 3)],
        "ms_per_decode_step_marginal": round(ms_step, 2),
        "prefill_plus_overhead_s": round(
            wall_long - ms_step * new_tokens / 1e3, 3
        ),
        "steady_tokens_per_sec_per_chip": round(
            out.stats["batch"] / (ms_step / 1e3), 1
        ),
        "decode_step_bytes_mb": round(step_bytes / 1e6, 1),
        "achieved_hbm_gbps": round(step_bytes / (ms_step / 1e3) / 1e9, 1),
        "decode_shape": out.stats,
    }


if __name__ == "__main__":
    batch = int(sys.argv[1]) if len(sys.argv) > 1 else 8
    new = int(sys.argv[2]) if len(sys.argv) > 2 else 32
    print(json.dumps(run(batch, new)))

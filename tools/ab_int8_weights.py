"""Live single-chip A/B: int8 weight kernel vs bf16 on the phase-1 sweep.

Proves the dequant-in-tile kernel is not a throughput regression on a model
that fits one chip both ways (llama3.2-1B by default; the 70B fit itself is
proven AOT in tools/prove_70b_int8_fit.py). Run on the TPU chip:

    python tools/ab_int8_weights.py [model] [reps]
"""

from __future__ import annotations

import dataclasses
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def run(model_name: str = "llama32-1b", reps: int = 3) -> dict:
    import jax

    from bench import MAX_NEW_TOKENS, build_sweep_prompts, decode_step_bytes
    from fairness_llm_tpu.config import ModelSettings
    from fairness_llm_tpu.models.configs import get_model_config
    from fairness_llm_tpu.runtime.engine import DecodeEngine

    prompts = build_sweep_prompts()
    settings = ModelSettings(
        temperature=0.7, top_k=0, top_p=1.0, max_tokens=MAX_NEW_TOKENS
    )
    out = {"model": model_name, "profiles": len(prompts)}
    for label in ("bf16", "int8"):
        cfg = get_model_config(model_name)
        if label == "int8":
            cfg = dataclasses.replace(cfg, weight_quant="int8")
        eng = DecodeEngine(cfg, seed=0)
        eng.generate(prompts, settings, seed=0)  # warmup/compile
        best = None
        for rep in range(reps):
            t0 = time.perf_counter()
            res = eng.generate(prompts, settings, seed=rep + 1)
            jax.block_until_ready(res.tokens)
            wall = time.perf_counter() - t0
            best = wall if best is None else min(best, wall)
        out[label] = {
            "best_wall_s": round(best, 3),
            "profiles_per_sec": round(len(prompts) / best, 2),
            "decode_shape": res.stats,
        }
        del eng
    out["int8_speedup"] = round(
        out["bf16"]["best_wall_s"] / out["int8"]["best_wall_s"], 3
    )
    return out


if __name__ == "__main__":
    name = sys.argv[1] if len(sys.argv) > 1 else "llama32-1b"
    reps = int(sys.argv[2]) if len(sys.argv) > 2 else 3
    print(json.dumps(run(name, reps)))

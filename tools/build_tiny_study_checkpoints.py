"""Build the tiny *real-architecture* study checkpoints under checkpoints/.

Purpose (VERDICT r2 item 1): the environment has no pretrained weights and no
egress, so the study's *numbers* on real Llama are blocked — but the *path* is
not. This script produces checkpoints with ``transformers`` itself (the same
machinery ``tests/test_hf_parity.py`` trusts as ground truth) and — so the
committed study record is non-vacuous — FINE-TUNES them (torch, CPU, seeded)
to speak the study's format:

- a byte-level BPE tokenizer trained to exactly 512 ids on the study's own
  prompt surfaces, saved per-checkpoint so ``backend_for`` picks it up via
  ``tokenizer_config.json``;
- ``tiny-llama-study``: LlamaForCausalLM (RoPE, GQA kv=2, SwiGLU, untied head);
- ``tiny-gpt2-study``: GPT2LMHeadModel (learned positions, LayerNorm, fused
  QKV Conv1D, tied head);
- both distilled from the deterministic ``SimulatedRecommender`` teacher —
  numbered-list recommendations (with a demographic-dependent bias signal and
  a weaker-bias response to fairness-instruction prompts, so phases 1 and 3
  measure something), listwise rankings, and pairwise A/B answers. The two
  models get teachers with different bias levels, so phase 2's cross-model
  comparison is non-vacuous.

Checkpoints are safetensors, ~6 MB each — committed. ``results/real_weights/``
is produced by running the CLI against these with ``--weights-dir
checkpoints``: the exact provenance chain (``backend_for -> load_checkpoint ->
HFTokenizer -> EngineBackend``) a real Llama checkpoint would take; the
reference's inference layer was always a real model
(``phase1_bias_detection.py:180-188``).

Run from the repo root:  python tools/build_tiny_study_checkpoints.py
"""

from __future__ import annotations

import json
import os
import sys

VOCAB = 512
OUT_DIR = "checkpoints"
SEQ_CAP = 768
# Teacher bias per model: distinct levels keep the cross-model phase-2
# comparison non-vacuous (the reference compares gpt-3.5 vs gpt-4 the same way).
TEACHER_BIAS = {"tiny-llama-study": 0.9, "tiny-gpt2-study": 0.35}
EPOCHS = 30
LR = 1e-3
BATCH = 8


def study_surfaces():
    """The study's own data/prompt objects, built once."""
    from fairness_llm_tpu.config import default_config
    from fairness_llm_tpu.data import (
        create_base_preferences,
        create_profile_grid,
        load_movielens,
    )
    from fairness_llm_tpu.data.ranking import create_synthetic_ranking_data

    config = default_config()
    data = load_movielens(config.data_dir, seed=config.random_seed)
    prefs = create_base_preferences(data, seed=config.random_seed)
    # More profiles than the study uses (6/combo vs 3) — the extra are plain
    # augmentation; the study's exact prompts are a subset, which is the point
    # of distillation (the model should do well on them).
    profiles = create_profile_grid(prefs, config, 6)
    items = create_synthetic_ranking_data(num_items=12, seed=config.random_seed)
    return config, data, prefs, profiles, items


def build_corpus(data, profiles, items) -> list:
    """Prompt-shaped tokenizer-training text from the pipeline's surfaces."""
    from fairness_llm_tpu.pipeline.prompts import (
        fairness_aware_prompt,
        listwise_prompt,
        pairwise_prompt,
        recommendation_prompt,
    )

    corpus = [recommendation_prompt(p) for p in profiles]
    corpus += [fairness_aware_prompt(p) for p in profiles[:5]]
    corpus.append(listwise_prompt(items))
    corpus += [pairwise_prompt(items[0], items[1]), pairwise_prompt(items[2], items[3])]
    corpus += list(data.titles)
    # numbered-list shapes the parsers expect, so digits/periods get merges
    corpus += [f"{i}. {t}" for i, t in enumerate(data.titles[:40], 1)]
    return corpus


def build_tokenizer(corpus):
    import tokenizers
    from tokenizers import decoders
    from tokenizers import models as tok_models
    from tokenizers import pre_tokenizers, trainers
    from transformers import PreTrainedTokenizerFast

    tok = tokenizers.Tokenizer(tok_models.BPE(unk_token=None))
    tok.pre_tokenizer = pre_tokenizers.ByteLevel(add_prefix_space=False)
    tok.decoder = decoders.ByteLevel()
    tok.train_from_iterator(
        corpus,
        trainers.BpeTrainer(vocab_size=VOCAB, special_tokens=["<|endoftext|>"]),
    )
    fast = PreTrainedTokenizerFast(tokenizer_object=tok, eos_token="<|endoftext|>")
    got = len(fast)
    if got != VOCAB:
        raise SystemExit(
            f"BPE trained to {got} ids, need exactly {VOCAB} (ModelConfig vocab "
            "is static) — enlarge the corpus in build_corpus()"
        )
    assert fast.eos_token_id == 0  # ModelConfig eos/pad_token_id pin this
    return fast


def build_models():
    import torch
    import transformers

    torch.manual_seed(0)
    llama = transformers.LlamaForCausalLM(transformers.LlamaConfig(
        vocab_size=VOCAB, hidden_size=128, intermediate_size=256,
        num_hidden_layers=4, num_attention_heads=4, num_key_value_heads=2,
        head_dim=32, max_position_embeddings=1024, rms_norm_eps=1e-5,
        rope_theta=10000.0, tie_word_embeddings=False, attention_bias=False,
        mlp_bias=False, eos_token_id=0, pad_token_id=0,
    ))
    torch.manual_seed(1)
    gpt2 = transformers.GPT2LMHeadModel(transformers.GPT2Config(
        vocab_size=VOCAB, n_positions=1024, n_embd=128, n_layer=4, n_head=4,
        activation_function="gelu_new", layer_norm_epsilon=1e-5,
        eos_token_id=0, pad_token_id=0,
    ))
    return {"tiny-llama-study": llama, "tiny-gpt2-study": gpt2}


def teacher_pairs(config, data, profiles, items, bias: float, seed: int):
    """(prompt, completion) distillation pairs from the simulated teacher."""
    from fairness_llm_tpu.pipeline.backends import SimulatedRecommender
    from fairness_llm_tpu.pipeline.prompts import (
        fairness_aware_prompt,
        listwise_prompt,
        pairwise_prompt,
        recommendation_prompt,
    )

    rec_teacher = SimulatedRecommender(
        data.titles, seed=config.random_seed, bias=bias
    )
    rank_teacher = SimulatedRecommender(
        [it.text for it in items], seed=config.random_seed, bias=bias,
        catalog_groups=[it.protected_attribute for it in items],
    )
    pairs = []
    # recommendation prompts — plain AND fairness-instructed (the teacher's
    # mitigation response is what gives phase 3 a measurable bias reduction)
    plain = [recommendation_prompt(p) for p in profiles]
    fair = [fairness_aware_prompt(p) for p in profiles]
    for pr, out in zip(plain, rec_teacher.generate(plain, seed=seed)):
        pairs.append((pr, out))
    for pr, out in zip(fair, rec_teacher.generate(fair, seed=seed)):
        pairs.append((pr, out))
    # listwise rankings over the study's item set, several sampled orders
    lw = [listwise_prompt(items)] + [
        listwise_prompt(items, query=f"topic {q}") for q in range(5)
    ]
    lw = lw * 4  # repetition with distinct teacher draws
    for i, (pr, out) in enumerate(zip(lw, rank_teacher.generate(lw, seed=seed, keys=[f"lw{i}" for i in range(len(lw))]))):
        pairs.append((pr, out))
    # pairwise comparisons over all ordered item pairs
    pw = [
        pairwise_prompt(items[a], items[b])
        for a in range(len(items)) for b in range(len(items)) if a != b
    ]
    for pr, out in zip(pw, rank_teacher.generate(pw, seed=seed)):
        pairs.append((pr, out))
    return pairs


def finetune(model, tokenizer, pairs, seed: int, epochs: int = EPOCHS):
    """Seeded CPU fine-tune: LM loss on the completion (+eos) only."""
    import torch

    rows = []
    for prompt, completion in pairs:
        p_ids = tokenizer.encode(prompt)
        c_ids = tokenizer.encode(completion) + [tokenizer.eos_token_id]
        ids = (p_ids + c_ids)[:SEQ_CAP]
        labels = ([-100] * len(p_ids) + c_ids)[:SEQ_CAP]
        rows.append((ids, labels))

    g = torch.Generator().manual_seed(seed)
    torch.manual_seed(seed)
    model.train()
    opt = torch.optim.AdamW(model.parameters(), lr=LR)
    steps = 0
    for epoch in range(epochs):
        order = torch.randperm(len(rows), generator=g).tolist()
        for start in range(0, len(order), BATCH):
            batch = [rows[i] for i in order[start : start + BATCH]]
            width = max(len(ids) for ids, _ in batch)
            input_ids = torch.zeros(len(batch), width, dtype=torch.long)
            labels = torch.full((len(batch), width), -100, dtype=torch.long)
            attn = torch.zeros(len(batch), width, dtype=torch.long)
            for i, (ids, lab) in enumerate(batch):
                input_ids[i, : len(ids)] = torch.tensor(ids)
                labels[i, : len(lab)] = torch.tensor(lab)
                attn[i, : len(ids)] = 1
            out = model(input_ids=input_ids, attention_mask=attn, labels=labels)
            out.loss.backward()
            torch.nn.utils.clip_grad_norm_(model.parameters(), 1.0)
            opt.step()
            opt.zero_grad()
            steps += 1
        if epoch % 5 == 0 or epoch == epochs - 1:
            print(f"  epoch {epoch}: loss {out.loss.item():.4f}")
    model.eval()
    return steps


def sanity_sample(model, tokenizer, prompt: str) -> str:
    """Greedy sample to eyeball format-following after training."""
    import torch

    ids = torch.tensor([tokenizer.encode(prompt)[-SEQ_CAP:]])
    with torch.no_grad():
        out = model.generate(
            ids, max_new_tokens=64, do_sample=False,
            pad_token_id=0, eos_token_id=0,
        )
    return tokenizer.decode(out[0, ids.shape[1]:], skip_special_tokens=True)


def main() -> int:
    sys.path.insert(0, os.getcwd())
    import transformers

    from fairness_llm_tpu.pipeline.parsing import parse_numbered_list
    from fairness_llm_tpu.pipeline.prompts import recommendation_prompt

    config, data, prefs, profiles, items = study_surfaces()
    corpus = build_corpus(data, profiles, items)
    tokenizer = build_tokenizer(corpus)
    for name, model in build_models().items():
        bias = TEACHER_BIAS[name]
        seed = 0 if "llama" in name else 1
        pairs = teacher_pairs(config, data, profiles, items, bias, seed)
        print(f"{name}: fine-tuning on {len(pairs)} teacher pairs (bias={bias})")
        steps = finetune(model, tokenizer, pairs, seed)
        sample = sanity_sample(model, tokenizer, recommendation_prompt(profiles[0]))
        parsed = parse_numbered_list(sample)
        print(f"  greedy sample parses to {len(parsed)} titles: {parsed[:3]}")

        path = os.path.join(OUT_DIR, name)
        os.makedirs(path, exist_ok=True)
        model.save_pretrained(path, safe_serialization=True)
        tokenizer.save_pretrained(path)
        with open(os.path.join(path, "PROVENANCE.json"), "w") as f:
            json.dump(
                {
                    "builder": "tools/build_tiny_study_checkpoints.py",
                    "transformers_version": transformers.__version__,
                    "seed": seed,
                    "teacher_bias": bias,
                    "finetune": {"epochs": EPOCHS, "lr": LR, "batch": BATCH,
                                 "steps": steps},
                    "tokenizer": "byte-level BPE, vocab 512, trained on the "
                                 "pipeline's own prompt surfaces",
                    "purpose": "prove the real-weights study path end to end "
                               "(VERDICT r2 item 1); distilled from the "
                               "SimulatedRecommender teacher, NOT a "
                               "pretrained model",
                },
                f, indent=1,
            )
        n_params = sum(p.numel() for p in model.parameters())
        print(f"{name}: {n_params} params -> {path}/")
    return 0


if __name__ == "__main__":
    sys.exit(main())

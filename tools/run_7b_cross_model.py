"""Live cross-model phase 2 at 7B scale: the BASELINE configs[2] set served
serially on the one real v5e chip.

The reference's cross-model ranking comparison
(phase2_cross_model_eval.py:319-432) evaluates each model over the same
corpus with listwise AND pairwise prompts and compares fairness. Here that
comparison runs over REAL 7B-class architectures — mistral-7b-int8,
qwen2-7b-int8, gemma-7b-int8 — each fitting the single chip via int8
dequant-in-tile weights (ops/quant_matmul.py), with random weights (bytes
and FLOPs representative; real checkpoints are a --weights-dir away).

Per-model serving notes (the chip is 15.75 GB):
- mistral/qwen2: params 7.4 / 8.2 GB; the 200-comparison pairwise batch's
  bf16 KV (~12.6 GB at batch 200 for mistral's 8 kv-heads) does NOT fit
  beside the params, so pairwise decodes in chunks (ChunkedEngineBackend).
- gemma: params 9.3 GB, but its MHA cache (16 kv heads x head_dim 256 =
  459 KB/slot bf16) is 4-8x the GQA models' — the listwise batch alone
  would need ~10.9 GB of bf16 KV. It runs with the int8 KV cache
  (kv_cache_quant, the capacity lever built for exactly this) and smaller
  pairwise chunks. "If it fits with cache" resolves to: bf16 NO, int8 YES.

    python tools/run_7b_cross_model.py [--quick]
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import sys
import time
from typing import List, Optional, Sequence

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# (chunk, kv_cache_quant): pairwise decode chunk size and cache mode chosen
# from the per-slot KV arithmetic above.
MODELS = {
    "mistral-7b-int8": {"chunk": 96, "kv_cache_quant": False},
    "qwen2-7b-int8": {"chunk": 128, "kv_cache_quant": False},
    "gemma-7b-int8": {"chunk": 32, "kv_cache_quant": True},
}


def _chunked(backend_cls):
    class ChunkedEngineBackend(backend_cls):
        """EngineBackend that splits generate() into <=chunk-row decodes.

        Exists because a 200-row pairwise batch's KV cache does not fit
        beside 7-9 GB of 7B params on one chip. Chunking changes default
        row seeds (they're positional), so outputs are deterministic PER
        CHUNK SIZE — the chunk size is pinned in the record's metadata.
        """

        def __init__(self, engine, chunk: int, name=None):
            super().__init__(engine, name=name)
            self.chunk = chunk

        def generate(self, prompts, settings=None, seed=0, keys=None,
                     prefix_ids=None) -> List[str]:
            out: List[str] = []
            for i in range(0, len(prompts), self.chunk):
                out.extend(
                    super().generate(
                        prompts[i : i + self.chunk], settings, seed=seed + i,
                        keys=None if keys is None else keys[i : i + self.chunk],
                        prefix_ids=prefix_ids,
                    )
                )
            return out

    return ChunkedEngineBackend


def run(num_items: int = 60, num_queries: int = 4, num_comparisons: int = 200,
        max_tokens: int = 128, models: Optional[Sequence[str]] = None) -> dict:
    import jax

    from fairness_llm_tpu.config import ModelSettings, default_config
    from fairness_llm_tpu.models.configs import get_model_config
    from fairness_llm_tpu.pipeline.backends import EngineBackend
    from fairness_llm_tpu.pipeline.phase2 import (
        build_corpus,
        compare_models_and_methods,
        evaluate_model,
    )
    from fairness_llm_tpu.runtime.engine import DecodeEngine

    config = default_config()
    items, prov = build_corpus(config, "movielens", num_items, with_provenance=True)
    settings = ModelSettings(temperature=0.7, top_k=0, top_p=1.0, max_tokens=max_tokens)
    Chunked = _chunked(EngineBackend)

    t_run = time.time()
    model_results = {}
    per_model_perf = {}
    for name in models or MODELS:
        opts = MODELS[name]
        cfg = get_model_config(name)
        if opts["kv_cache_quant"]:
            cfg = dataclasses.replace(cfg, kv_cache_quant=True)
        t0 = time.time()
        eng = DecodeEngine(cfg, seed=0)
        jax.block_until_ready(jax.tree.leaves(eng.params)[0])
        init_s = time.time() - t0
        param_gb = sum(x.nbytes for x in jax.tree.leaves(eng.params)) / 1e9
        backend = Chunked(eng, chunk=opts["chunk"], name=name)
        t0 = time.time()
        model_results[name] = evaluate_model(
            backend, items, num_comparisons, settings,
            seed=config.random_seed, num_queries=num_queries,
        )
        eval_s = time.time() - t0
        per_model_perf[name] = {
            "init_s": round(init_s, 1),
            "param_tree_gb": round(param_gb, 2),
            "kv_cache_quant": opts["kv_cache_quant"],
            "pairwise_chunk": opts["chunk"],
            "eval_wall_s": round(eval_s, 1),
            # one listwise batch + chunked pairwise + scored + perplexity,
            # compiles included — the end-to-end number a study run pays
            "eval_calls_per_sec": round(
                (num_queries + num_comparisons) / eval_s, 2
            ),
        }
        print(f"{name}: init {init_s:.0f}s eval {eval_s:.0f}s", file=sys.stderr)
        del backend, eng

    results = {
        "metadata": {
            "phase": 2,
            "variant": "7b-cross-model-live",
            "device": str(jax.devices()[0]),
            "models": list(models or MODELS),
            "corpus": "movielens",
            "corpus_provenance": prov,
            "num_items": len(items),
            "num_queries": num_queries,
            "num_comparisons": num_comparisons,
            "max_tokens": max_tokens,
            "weights": "random-init (bytes/FLOPs representative; see tool docstring)",
            "timestamp": time.time(),
            "elapsed_seconds": round(time.time() - t_run, 1),
        },
        "items": [vars(it) for it in items],
        "per_model_perf": per_model_perf,
        "model_results": model_results,
        "comparison": compare_models_and_methods(model_results),
    }
    return results


if __name__ == "__main__":
    logging.basicConfig(level=logging.INFO)
    quick = "--quick" in sys.argv
    res = run(
        num_items=12 if quick else 60,
        num_queries=2 if quick else 4,
        num_comparisons=8 if quick else 200,
        max_tokens=16 if quick else 128,
        models=["mistral-7b-int8"] if quick else None,
    )
    out_path = os.path.join(ROOT, "results", "phase2", "phase2_7b_results.json")
    if quick:
        out_path = "/tmp/phase2_7b_quick.json"
    from fairness_llm_tpu.pipeline import results as R

    R.save_results(res, out_path)
    print(json.dumps({
        "wrote": out_path,
        "per_model_perf": res["per_model_perf"],
        "model_fairness": res["comparison"]["model_fairness"],
    }))

"""AOT proof: llama3-70b int8 weights, tp=8, fits one v5e-8 slice.

Compiles the REAL prefill+decode program for the ``llama3-70b-int8`` config
against an eight-chip v5e topology DESCRIPTOR (``jax.experimental.topologies``
— the actual v5e TPU compiler, no 8-chip hardware needed) and reads the
compiled program's own memory analysis. This is the check that flips round
2/3's honest negative: bf16 70B at tp=8 is ~17.6 GB/chip (over a v5e's HBM),
and the naive int8-dequant-at-use program hoists a 35 GB bf16 tree
(docs/PERFORMANCE.md round 3). With dequant-in-tile (ops/quant_matmul.py)
the int8 tree IS the resident form.

Run: python tools/prove_70b_int8_fit.py            (~several minutes: 80
     unrolled layers x 7 Pallas matmuls each through the Mosaic pipeline)
Prints one JSON line; also used by bench.py when BENCH_70B_PROOF=1.
"""

from __future__ import annotations

import json
import os
import sys
import time

# Runtime (not PYTHONPATH) path fix: prepending the repo root via PYTHONPATH
# shadows a module the axon TPU plugin imports during site init and kills
# backend registration; inserting here runs after site init and is safe.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

V5E_HBM_GB = 15.75  # usable HBM the TPU compiler enforces on a 16 GB v5e


def prove(model_name: str = "llama3-70b-int8", batch: int = 8,
          prompt_len: int = 128, new_tokens: int = 4,
          num_layers: int | None = None) -> dict:
    """``num_layers`` override: a 2-layer variant exercises the identical
    per-layer lowering (kernels, shard_map, collectives) in ~1/40th the
    compile time — bench.py uses it for the in-run lowering check while the
    committed artifact holds the full-model memory analysis."""
    import dataclasses

    import flax.linen as nn
    import jax
    import jax.numpy as jnp
    import jax.tree_util as jtu
    import numpy as np
    from jax.experimental import topologies
    from jax.sharding import Mesh

    from fairness_llm_tpu.models.configs import get_model_config
    from fairness_llm_tpu.models.transformer import Transformer, init_cache
    from fairness_llm_tpu.ops.quant_matmul import force_pallas
    from fairness_llm_tpu.parallel import sharding as shd

    # jax 0.4.x jaxlib SIGABRTs (a fatal Mosaic layout check, not a Python
    # error) compiling these programs against a TPU topology descriptor —
    # fail as a catchable error so bench.py's fail-soft wrapper records
    # "lowering unavailable" instead of the whole bench process dying.
    if tuple(int(x) for x in jax.__version__.split(".")[:2]) < (0, 6):
        raise RuntimeError(
            f"TPU-topology AOT compile needs jax >= 0.6 (have {jax.__version__}; "
            "0.4.x jaxlib hard-crashes in Mosaic on these programs)"
        )

    cfg = get_model_config(model_name)
    if num_layers is not None:
        cfg = dataclasses.replace(
            cfg, name=f"{cfg.name}-{num_layers}l", num_layers=num_layers
        )
    td = topologies.get_topology_desc(platform="tpu", topology_name="v5e:2x4")
    mesh = Mesh(np.array(td.devices).reshape(1, 8, 1), ("dp", "tp", "sp"))
    rules = shd.make_axis_rules(cfg, mesh)
    shardings = shd.param_shardings(cfg, mesh, rules)

    model = Transformer(cfg)
    abstract = nn.meta.unbox(
        jax.eval_shape(
            model.init, jax.random.key(0),
            jnp.zeros((1, 8), jnp.int32), jnp.zeros((1, 8), jnp.int32),
        )["params"]
    )
    flat, treedef = jtu.tree_flatten_with_path(abstract)
    aleaves = []
    for (path, leaf), s in zip(flat, jtu.tree_leaves(shardings)):
        name = getattr(path[-1], "key", "")
        # Engine storage policy for a big bf16 model: float leaves in bf16,
        # quant scales kept f32, int8 kernels stay int8.
        if not jnp.issubdtype(leaf.dtype, jnp.floating):
            dt = leaf.dtype
        else:
            dt = jnp.float32 if name == "kernel_scale" else jnp.bfloat16
        aleaves.append(jax.ShapeDtypeStruct(leaf.shape, dt, sharding=s))
    aparams = jtu.tree_unflatten(treedef, aleaves)

    B, S, NEW = batch, prompt_len, new_tokens

    def prefill_and_decode(params, tokens, positions, valid):
        # The engine's program shape (runtime/engine.py): batch prefill
        # writes the cache, then cached single-token steps extend it.
        cache = init_cache(cfg, B, S + NEW)
        logits, cache = model.apply(
            {"params": params}, tokens, positions, valid, cache,
            left_padded=True, last_only=True,
        )

        def step(_, carry):
            logits, cache = carry
            tok = jnp.argmax(logits[:, -1, :], axis=-1).astype(jnp.int32)
            pos = cache.lengths[:, None]
            logits, cache = model.apply(
                {"params": params}, tok[:, None], pos,
                jnp.ones((B, 1), jnp.bool_), cache,
            )
            return logits, cache

        logits, _ = jax.lax.fori_loop(0, NEW, step, (logits, cache))
        return logits

    bs = shd.batch_sharding(mesh)
    atoks = jax.ShapeDtypeStruct((B, S), jnp.int32, sharding=bs)
    apos = jax.ShapeDtypeStruct((B, S), jnp.int32, sharding=bs)
    avalid = jax.ShapeDtypeStruct((B, S), jnp.bool_, sharding=bs)
    t0 = time.time()
    with mesh, nn.logical_axis_rules(rules), force_pallas():
        compiled = (
            jax.jit(prefill_and_decode).lower(aparams, atoks, apos, avalid).compile()
        )
    ma = compiled.memory_analysis()
    total_gb = (
        ma.argument_size_in_bytes + ma.temp_size_in_bytes + ma.output_size_in_bytes
    ) / 1e9
    return {
        "model": cfg.name,
        "topology": "v5e:2x4 (tp=8)",
        "batch": B,
        "prompt_len": S,
        "decode_steps": NEW,
        "compile_s": round(time.time() - t0, 1),
        "args_gb_per_chip": round(ma.argument_size_in_bytes / 1e9, 2),
        "temps_gb_per_chip": round(ma.temp_size_in_bytes / 1e9, 2),
        "output_gb_per_chip": round(ma.output_size_in_bytes / 1e9, 3),
        "total_gb_per_chip": round(total_gb, 2),
        "hbm_limit_gb": V5E_HBM_GB,
        "fits": bool(total_gb < V5E_HBM_GB),
        "analytic_param_gb_per_chip": round(
            shd.per_device_param_bytes(cfg, mesh, rules) / 1e9, 2
        ),
    }


if __name__ == "__main__":
    print(json.dumps(prove()))

"""Live A/B: fused int8-KV decode-attention kernel vs XLA, large-batch sweep.

The round-3 bf16 kernel lost to XLA's fusions (~8% at batch 48); the int8
variant is the one kernel target with a byte-reduction story — at batch
192/360 decode is KV-bound and int8-KV already wins +24% through plain XLA
despite its dequant cost (docs/PERFORMANCE.md). This measures whether
dequant-in-tile beats XLA's fused dequant at the shapes that matter.

    python tools/ab_int8kv_kernel.py [model] [mults...]   # default gpt2-small 4 8
"""

from __future__ import annotations

import dataclasses
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def run(model_name: str = "gpt2-small", mults=(4, 8)) -> dict:
    import jax

    from bench import MAX_NEW_TOKENS, build_sweep_prompts
    from fairness_llm_tpu.config import ModelSettings
    from fairness_llm_tpu.models.configs import get_model_config
    from fairness_llm_tpu.runtime.engine import DecodeEngine

    base_prompts = build_sweep_prompts()
    settings = ModelSettings(
        temperature=0.7, top_k=0, top_p=1.0, max_tokens=MAX_NEW_TOKENS
    )
    out = {"model": model_name}
    for mult in mults:
        prompts = list(base_prompts) * mult
        row = {}
        for label, kernel in (("xla", False), ("kernel", True)):
            cfg = dataclasses.replace(
                get_model_config(model_name),
                kv_cache_quant=True,
                use_decode_attention_kernel=kernel,
            )
            eng = DecodeEngine(cfg, seed=0)
            eng.generate(prompts, settings, seed=0)  # warmup/compile
            best = None
            for rep in range(3):
                t0 = time.perf_counter()
                res = eng.generate(prompts, settings, seed=rep + 1)
                jax.block_until_ready(res.tokens)
                wall = time.perf_counter() - t0
                best = wall if best is None else min(best, wall)
            row[label] = {
                "best_wall_s": round(best, 3),
                "profiles_per_sec": round(len(prompts) / best, 2),
                "decode_shape": res.stats,
            }
            del eng
        row["kernel_speedup"] = round(
            row["xla"]["best_wall_s"] / row["kernel"]["best_wall_s"], 3
        )
        out[f"x{mult}"] = row
    return out


if __name__ == "__main__":
    name = sys.argv[1] if len(sys.argv) > 1 else "gpt2-small"
    mults = [int(a) for a in sys.argv[2:]] or [4, 8]
    print(json.dumps(run(name, mults)))

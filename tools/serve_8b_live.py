"""Serve BASELINE configs[1] — Llama-3-8B — WHOLE on the one real v5e chip.

The first end-to-end >=7B full-model measurement in the project: every
earlier >=7B data point was an AOT topology compile (tests/test_70b_readiness)
or a 1/8 tp-shard (tools/measure_70b_shard.py). This runs the actual
flagship config the hardware can serve — llama3-8b-int8, ~8.6 GB of
dequant-in-tile int8 weights (ops/quant_matmul.py) on a 15.75 GB chip,
leaving ~7 GB for KV — through BOTH study workloads:

- the phase-1 45-profile counterfactual sweep (the decode-bound hot loop the
  reference runs as sequential API calls, phase1_bias_detection.py:325-340),
  with the decode-step MARGINAL measured by diffing two decode lengths so
  prefill can't smear the step time;
- a phase-2 60-item listwise ranking batch (the prefill-bound workload,
  phase2_cross_model_eval.py:319-432), flash prefill.

Weights are randomly initialized: values change neither FLOPs nor bytes
streamed, so throughput/bandwidth are representative (project convention
since round 1); real Llama weights are a --weights-dir flag away.

    python tools/serve_8b_live.py            # full (also writes the proof)
    python tools/serve_8b_live.py --no-save  # measure only
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

V5E_HBM_GB = 15.75  # the v5e compiler's own HBM figure (memory_stats is
# unavailable over the tunneled-device backend)


def run(max_new: int = 128, include_probe: bool = True,
        kv_quant: bool = False, skip_listwise: bool = False) -> dict:
    """``kv_quant=True`` serves the sweep with the int8 KV cache on top of
    the int8 weights — the KV/prefix reads are ~2.3 GB of the 10 GB step,
    so halving them probes whether the 8B operating point is KV-bound the
    way the gpt2 batch-360 curve is."""
    import dataclasses

    import jax

    from bench import (
        build_listwise_prompts,
        build_sweep_prompts,
        decode_step_bytes,
        measure_achievable_gbps,
    )
    from fairness_llm_tpu.config import ModelSettings
    from fairness_llm_tpu.models.configs import get_model_config
    from fairness_llm_tpu.runtime.engine import DecodeEngine

    if max_new < 16:
        raise ValueError(
            "max_new must be >= 16: the marginal-step measurement diffs "
            "decode lengths max_new and max(8, max_new//4)"
        )
    config = get_model_config("llama3-8b-int8")
    if kv_quant:
        config = dataclasses.replace(config, kv_cache_quant=True)
    t0 = time.time()
    eng = DecodeEngine(config, seed=0)
    jax.block_until_ready(jax.tree.leaves(eng.params)[0])
    init_s = time.time() - t0
    param_bytes = sum(x.nbytes for x in jax.tree.leaves(eng.params))

    prompts = build_sweep_prompts()  # the 45-profile grid

    def timed(new):
        settings = ModelSettings(temperature=0.7, top_k=0, top_p=1.0, max_tokens=new)
        t0 = time.time()
        eng.generate(prompts, settings, seed=0)  # compile + warmup
        compile_s = time.time() - t0
        best, out = None, None
        for rep in range(2):
            t0 = time.perf_counter()
            out = eng.generate(prompts, settings, seed=rep + 1)
            jax.block_until_ready(out.tokens)
            wall = time.perf_counter() - t0
            best = wall if best is None else min(best, wall)
        return best, compile_s, out

    short = max(8, max_new // 4)
    wall_short, compile_a, _ = timed(short)
    wall_long, compile_b, out = timed(max_new)
    ms_step = (wall_long - wall_short) / (max_new - short) * 1e3

    step_bytes = decode_step_bytes(config, out.stats)
    achievable = measure_achievable_gbps() if include_probe else None

    # HBM occupancy at the sweep operating point: exact param-tree bytes +
    # the analytic KV/prefix accounting the roofline model uses.
    per_head_slot = (config.head_dim + 4) if config.kv_cache_quant else (
        config.head_dim * 2
    )
    per_slot = config.num_kv_heads * per_head_slot * 2 * config.num_layers
    kv_bytes = out.stats["batch"] * out.stats["cache_slots"] * per_slot
    prefix_bytes = out.stats["prefix_len"] * per_slot
    used_gb = (param_bytes + kv_bytes + prefix_bytes) / 1e9

    result = {
        "model": config.name + ("+int8kv" if kv_quant else ""),
        "baseline_config": "BASELINE.json configs[1]: Llama-3-8B, TP=1, one chip",
        "init_s": round(init_s, 1),
        "param_tree_gb": round(param_bytes / 1e9, 2),
        "phase1_sweep": {
            "profiles": len(prompts),
            "max_new_tokens": max_new,
            "compile_plus_warmup_s": round(compile_a + compile_b, 1),
            "walls_s": [round(wall_short, 3), round(wall_long, 3)],
            "profiles_per_sec": round(len(prompts) / wall_long, 2),
            "ms_per_decode_step_marginal": round(ms_step, 2),
            # Live-row rate: tokens a CLIENT receives per second. The
            # bucketed batch (48) decodes 3 pad rows whose tokens nobody
            # reads — the padded rate stays as the device-throughput view.
            "steady_tokens_per_sec": round(len(prompts) / (ms_step / 1e3), 1),
            "steady_tokens_per_sec_padded": round(
                out.stats["batch"] / (ms_step / 1e3), 1
            ),
            "decode_shape": out.stats,
            "decode_step_bytes_mb": round(step_bytes / 1e6, 1),
            "achieved_hbm_gbps": round(step_bytes / (ms_step / 1e3) / 1e9, 1),
            "achievable_hbm_gbps_probe": (
                round(achievable, 1) if achievable else None
            ),
            "achieved_over_achievable": (
                round(step_bytes / (ms_step / 1e3) / 1e9 / achievable, 3)
                if achievable
                else None
            ),
            "hbm_used_gb": round(used_gb, 2),
            "hbm_limit_gb": V5E_HBM_GB,
            "hbm_headroom_gb": round(V5E_HBM_GB - used_gb, 2),
        },
    }

    if skip_listwise:
        del eng
        return result

    # Phase-2 listwise on the SAME live engine (flash prefill; head_dim 128).
    # share_prefix=False so the flash kernel actually runs (the auto-detected
    # ~64-token shared prefix would route prefill through the dense joint
    # path — round-4 finding).
    try:
        lw_prompts, lw_items, _ = build_listwise_prompts()
        settings = ModelSettings(temperature=0.7, top_k=0, top_p=1.0, max_tokens=32)
        t0 = time.time()
        eng.generate(lw_prompts, settings, seed=0, share_prefix=False)
        lw_compile = time.time() - t0
        best = None
        for rep in range(2):
            t0 = time.perf_counter()
            res = eng.generate(lw_prompts, settings, seed=rep + 1, share_prefix=False)
            jax.block_until_ready(res.tokens)
            wall = time.perf_counter() - t0
            best = wall if best is None else min(best, wall)
        result["phase2_listwise"] = {
            "num_items": len(lw_items),
            "num_queries": len(lw_prompts),
            "compile_s": round(lw_compile, 1),
            "wall_s": round(best, 3),
            "queries_per_sec": round(len(lw_prompts) / best, 3),
            "decode_shape": res.stats,
        }
    except Exception as e:  # noqa: BLE001 — auxiliary measurement only
        print(f"8B phase-2 listwise skipped: {type(e).__name__}: {e}", file=sys.stderr)
        result["phase2_listwise"] = {"error": f"{type(e).__name__}: {e}"}

    del eng
    return result


if __name__ == "__main__":
    res = run()
    print(json.dumps(res))
    if "--no-save" not in sys.argv:
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        path = os.path.join(root, "results", "proofs", "llama3_8b_live.json")
        with open(path, "w") as f:
            json.dump(res, f, indent=1)
        print(f"wrote {path}", file=sys.stderr)

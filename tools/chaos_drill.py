"""Chaos drill: scripted faults + injected hang + silent corruption + a real
mid-run SIGTERM, then resume — the end-to-end proof behind
docs/RESILIENCE.md (resilience AND integrity layers).

What it does, in one process, deterministically:

1. builds a tiny CPU engine (numerics guards armed) and records an
   UNINTERRUPTED baseline (the greedy tokens every request should decode);
2. re-serves the same workload through a resilience-armed scheduler with a
   scripted fault mix (one transient decode fault, one permanent one, one
   prefill fault), one injected hang (watchdog-classified, no real sleep),
   one injected NaN corruption (guard-classified ``NumericsFault``), and a
   journal — and raises a REAL ``SIGTERM`` at itself the moment the late
   cohort reaches decode, so the ``GracefulDrain`` handler drains the run
   mid-flight;
3. resumes the journal's unfinished requests (``resume_serving``) in a
   fresh scheduler;
4. drills the at-rest integrity path: exports the engine's weights with a
   sha256 manifest, flips one BIT in the shard, and asserts the load is
   refused with an error naming the file;
5. drills the canary: a golden-prompt probe through a live scheduler
   matches its static-engine reference, then a tampered reference
   (standing in for silently-corrupt serving output) trips the decode
   breaker and the degradation ladder;
6. drills the REPLICA FLEET (ISSUE 6): serves the same workload through a
   2-replica ``ReplicaSet`` and kills replica r1 mid-sweep (scripted
   ``replica_crash``) — asserting zero lost requests, migrated survivors
   token-identical to the single-engine greedy baseline, the healthy
   replica serving throughout, and the killed replica rejoining through
   its canary warm-up probe (``fleet_healthy_replicas`` back to 2);
7. drills OVERLOAD CONTROL (ISSUE 8): sheds a provably-doomed deadline at
   admission (no prefill burned), then offers ~3x the queue's capacity
   with mixed QoS classes — asserting interactive TTFT p95 holds its SLO
   while batch sheds with explicit retry-after Results, zero
   accepted-then-lost requests, nonzero ``shed_total`` counters, and the
   controller de-escalating to level 0 after the flood
   (``validate_telemetry --require-overload`` gates it);
8. drills FAIRNESS OBSERVABILITY (ISSUE 9): byte-identical counterfactual
   pair probes (same prompt, different group tag) through a fault-free
   scheduler stay SILENT — every pair joins with zero divergence and no
   neutrality alert — then the same workload with decode faults targeted
   at ONE group's requests must raise ``fairness_alerts_total`` (group
   disparity in the impaired-rate audit) and count the divergent pairs
   with their members' serving events attributed (the requeues the
   injected faults caused); the rendered fairness report is written
   beside the snapshot (``fairness_report.txt``) for failure evidence;
9. drills the PAGED KV CACHE (ISSUE 10): a counterfactual-shaped prompt
   family through a ``--paged-kv`` scheduler with a scarce block arena —
   a mid-sweep decode fault requeues a request whose prefix blocks are
   SHARED with a live twin; asserting zero lost, every survivor
   token-identical to the engine baseline (a stale or wrongly-freed
   block would corrupt a survivor's tokens), the requeue re-admitted
   through the radix index (nonzero hit tokens), and block accounting
   whole at drain;
10. drills the FUSED DISPATCH (ISSUE 14): the same workload through a
   ``--fuse-steps 4`` scheduler with an injected NaN landing INSIDE a
   fused window (four chunks in one compiled call) — the guard flag rides
   the fused carry, the whole dispatch is discarded at its boundary as
   one ``NumericsFault``, the poisoned rider requeues once, and every
   survivor decodes token-identical (fusion widens the blast radius per
   fault, never the outcome);
11. validates the ISSUE-4/5/6 acceptance: every request terminal (zero
   lost), survivors token-for-token equal to the baseline (zero corrupt
   records — the NaN chunk was retried, not delivered), the breaker cycle
   + hang + numerics fault + manifest failure + canary mismatch + fleet
   fence/migrate/rejoin all present in the telemetry snapshot
   (``validate_telemetry --require-fleet`` gates the fleet half), and the
   journal empty.

Usage (CI runs exactly this):
    JAX_PLATFORMS=cpu python tools/chaos_drill.py --telemetry-dir chaos-tel
"""

from __future__ import annotations

import argparse
import os
import signal
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from fairness_llm_tpu.config import ModelSettings, ResilienceConfig, ServingConfig  # noqa: E402
from fairness_llm_tpu.models.configs import get_model_config  # noqa: E402
from fairness_llm_tpu.resilience import (  # noqa: E402
    GracefulDrain,
    ServingJournal,
    resume_serving,
)
from fairness_llm_tpu.runtime.engine import DecodeEngine  # noqa: E402
from fairness_llm_tpu.serving import ContinuousScheduler, Request, Result  # noqa: E402
from fairness_llm_tpu.utils.failures import ScriptedFaultInjector  # noqa: E402

GREEDY = ModelSettings(temperature=0.0, max_tokens=8)
SERVING = ServingConfig(enabled=True, num_slots=2, queue_capacity=64,
                        max_prompt_len=192, max_new_tokens=32, decode_chunk=4)
# Generous watchdog budget: only the injector's SIMULATED 3600 s stalls may
# classify as hangs — a real chunk on a loaded CI runner (first one includes
# XLA compilation) must never trip it, or the drill turns flaky.
RESILIENCE = ResilienceConfig(enabled=True, max_step_seconds=120.0,
                              breaker_threshold=1, breaker_cooldown_s=0.02,
                              drain_grace_s=30.0)

PROMPTS = {
    "ok0": "the quick brown fox",
    "flaky": "hello there friend",      # one transient decode fault
    "doomed": "abc abc abc abc abc",    # permanent decode fault -> failed
    "pfault": "one two three one two",  # one prefill fault
    "hangme": "recommend ten films please",  # one injected hang
    "nanme": "name five good books",    # one injected NaN corruption
    "late0": "zz zz zz",                # reaching decode triggers SIGTERM
    "late1": "a long prompt that shifts padding and lands in a bucket",
}


class SigtermOnSight(ScriptedFaultInjector):
    """Raises a real SIGTERM at our own process the first time the late
    cohort reaches decode — the GracefulDrain handler (installed around the
    serve) turns it into a drain request the scheduler honors at its next
    loop iteration. Deterministic 'preemption notice mid-run'."""

    def __init__(self, faults, hangs, corruptions=None):
        super().__init__(faults, hangs=hangs, corruptions=corruptions)
        self._fired_sigterm = False

    def maybe_fail(self, request_id, stage):
        if request_id == "late0" and stage == "decode" and not self._fired_sigterm:
            self._fired_sigterm = True
            signal.raise_signal(signal.SIGTERM)
        super().maybe_fail(request_id, stage)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--telemetry-dir", default=None,
                    help="write events.jsonl + the validated snapshot here")
    ap.add_argument("--trace-out", default=None,
                    help="write the device-step timeline as Chrome-trace "
                         "JSON (default <telemetry-dir>/trace.json) — the "
                         "drill's faults/fences/migrations render as "
                         "per-replica Perfetto lanes")
    ap.add_argument("--journal-dir", default=None,
                    help="serving journal dir (default: a temp dir)")
    a = ap.parse_args()

    from fairness_llm_tpu import telemetry as T

    sink = T.configure(a.telemetry_dir) if a.telemetry_dir else None
    journal_dir = a.journal_dir or tempfile.mkdtemp(prefix="chaos-journal-")

    # Incident engine (ISSUE 13): armed for the WHOLE drill with a cooldown
    # longer than any CI run, so per-(class, scope) dedup is absolute —
    # each injected fault family must produce EXACTLY one bundle however
    # many faults the storm lands. Bundles ride the telemetry artifact.
    inc_dir = os.path.join(
        a.telemetry_dir or tempfile.mkdtemp(prefix="chaos-incidents-"),
        "incidents",
    )
    T.arm_incidents(inc_dir, cooldown_s=3600.0)

    problems = []

    def check(ok: bool, what: str) -> None:
        print(("PASS" if ok else "FAIL") + f"  {what}")
        if not ok:
            problems.append(what)

    def bundles(cls: str, scope: str = None):
        found = [m for m in T.list_bundles(inc_dir) if m["class"] == cls]
        if scope is not None:
            found = [m for m in found if m.get("scope") == scope]
        return found

    def bundle_fault_ids(manifest) -> set:
        """Request ids named by the bundle's fault decisions — the
        'decision trail names the injected cause' witness."""
        import json as _json

        ids = set()
        with open(os.path.join(manifest["path"], "decisions.jsonl"),
                  encoding="utf-8") as f:
            for line in f:
                try:
                    d = _json.loads(line)
                except _json.JSONDecodeError:
                    continue
                if d.get("decision") == "fault":
                    ids.update((d.get("signals") or {}).get(
                        "request_ids", ()))
        return ids

    # Numerics guards armed: the injected NaN below must be caught by the
    # on-device finite flag, not delivered as garbage tokens.
    engine = DecodeEngine(get_model_config("tiny-test"), seed=0,
                          numerics_guards=True)

    # 1. Uninterrupted baseline: the tokens every survivor must reproduce.
    baseline = {}
    for rid, prompt in PROMPTS.items():
        out = engine.generate([prompt], GREEDY)
        baseline[rid] = np.asarray(out.tokens[0])

    # 2. The chaos run.
    journal = ServingJournal(journal_dir)
    inj = SigtermOnSight(
        faults={("flaky", "decode"): 1, ("doomed", "decode"): 2,
                ("pfault", "prefill"): 1},
        hangs={("hangme", "decode"): 1},
        corruptions={("nanme", "decode"): 1},
    )
    sched = ContinuousScheduler(engine, SERVING, settings=GREEDY,
                                fault_injector=inj, resilience=RESILIENCE,
                                journal=journal)
    reqs = [Request(prompt=p, id=rid, settings=GREEDY)
            for rid, p in PROMPTS.items()]
    with GracefulDrain():
        results = {r.id: r for r in sched.serve(reqs)}
    preempted = sorted(rid for rid, r in results.items()
                       if r.finish_reason == "preempted")
    print(f"chaos run: { {rid: r.finish_reason for rid, r in results.items()} }")
    check(set(results) == set(PROMPTS), "every request got a phase-1 Result")
    check(bool(preempted), "SIGTERM drained a late cohort to the journal")
    check(inj.hangs_fired == [("hangme", "decode")], "the hang fired once")
    check(inj.corruptions_fired == [("nanme", "decode")],
          "the NaN corruption fired once")
    check(sorted(r["id"] for r in journal.unfinished()) == preempted,
          "journal unfinished == preempted set")

    # Incident bundles for the section-2 fault families (ISSUE 13): the
    # fault STORM above (five scripted faults, re-opens, a hang, a NaN)
    # must dedup to exactly one bundle per family, each one's decision
    # trail naming the injected cause.
    injected = {"flaky", "doomed", "pfault", "hangme", "nanme"}
    bo = bundles("breaker_open", scope="serving")
    check(len(bo) == 1 and bool(bundle_fault_ids(bo[0]) & injected),
          "exactly one breaker_open bundle; decision trail names the "
          f"injected request(s) ({sorted(bundle_fault_ids(bo[0]) & injected) if bo else '-'})")
    wh = bundles("watchdog_hang")
    check(len(wh) == 1
          and "hangme" in wh[0].get("context", {}).get("request_ids", ()),
          "exactly one watchdog_hang bundle naming 'hangme'")
    nf = bundles("numerics_fault")
    check(len(nf) == 1
          and "nanme" in nf[0].get("context", {}).get("request_ids", ()),
          "exactly one numerics_fault bundle naming 'nanme'")

    # 3. Resume.
    resumed = resume_serving(engine, journal, serving=SERVING,
                             resilience=RESILIENCE)
    check(sorted(resumed) == preempted, "resume served exactly the journal")
    check(journal.unfinished() == [], "journal empty after resume")

    # 4. Acceptance: zero lost + survivor parity + breaker cycle visible.
    final = {**results, **resumed}
    lost = set(PROMPTS) - set(final)
    check(not lost, f"zero lost requests (missing: {sorted(lost) or 'none'})")
    check(not final["doomed"].ok and final["doomed"].finish_reason == "failed",
          "permanent fault terminated failed (requeue-once, not forever)")
    parity_ok, survivors = True, 0
    for rid, res in final.items():
        if not res.ok:
            continue
        survivors += 1
        n = len(res.tokens)
        ref = baseline[rid]
        if n == 0 or not np.array_equal(np.asarray(res.tokens), ref[:n]) \
                or not np.all(ref[n:] == engine.tokenizer.pad_id):
            parity_ok = False
            print(f"  parity break: {rid}: {list(res.tokens)} vs {list(ref)}")
    # Survivor floor: each chunk-wide fault (decode fault, hang, NaN chunk)
    # requeues BOTH riders of the 2-slot pool, so with five scripted faults
    # a rider can legitimately burn its single requeue on someone else's
    # fault and terminate failed — terminal and visible, never lost or
    # corrupt. 5-of-8 is this script's deterministic outcome; the hard
    # guarantees are the per-request checks around it.
    check(parity_ok and survivors >= len(PROMPTS) - 3,
          f"{survivors} survivors all token-for-token with baseline")
    nan_res = final["nanme"]
    check(nan_res.ok and np.array_equal(
              np.asarray(nan_res.tokens),
              baseline["nanme"][: len(nan_res.tokens)]),
          "NaN-corrupted request contained + retried to clean tokens")

    # 4. At-rest integrity: a bit-flipped weight shard must be REFUSED at
    # load with a manifest-digest error naming the file.
    from fairness_llm_tpu.integrity.manifest import IntegrityError  # noqa: E402
    from fairness_llm_tpu.runtime.weights import (  # noqa: E402
        load_checkpoint,
        save_checkpoint_hf,
    )

    wdir = tempfile.mkdtemp(prefix="chaos-weights-")
    save_checkpoint_hf(engine.config, engine.params, wdir)
    shard = os.path.join(wdir, "model.safetensors")
    # Clean round-trip first: the manifest must accept what it just hashed.
    load_checkpoint(engine.config, wdir)
    # Flip one bit deep in the tensor data region (past the header).
    ScriptedFaultInjector.flip_bit(shard, (os.path.getsize(shard) - 64) * 8)
    try:
        load_checkpoint(engine.config, wdir)
        check(False, "bit-flipped shard refused at load")
    except IntegrityError as e:
        check("model.safetensors" in str(e),
              f"bit-flipped shard refused, error names the file ({e})")
    itf = bundles("integrity_fault")
    check(len(itf) == 1 and "model.safetensors" in itf[0]["cause"],
          "exactly one integrity_fault bundle naming the flipped shard")

    # 5. Canary: golden-prompt probe through a live scheduler matches the
    # static-engine reference; a tampered reference (the comparator's view
    # of silently-corrupt serving output) trips the degradation ladder.
    from fairness_llm_tpu.integrity.canary import CanaryProbe  # noqa: E402
    from fairness_llm_tpu.resilience import BreakerBoard  # noqa: E402

    board = BreakerBoard(failure_threshold=3, cooldown_s=60.0)
    canary_sched = ContinuousScheduler(engine, SERVING, settings=GREEDY,
                                       breakers=board)
    probe = CanaryProbe.record(engine, max_tokens=8, every_n=1, board=board)
    check(probe.probe(canary_sched), "canary matches on a healthy scheduler")
    probe.reference = probe.reference.copy()
    probe.reference[0] += 1  # silent corruption, as the comparator sees it
    check(not probe.probe(canary_sched) and board.state("decode") == "open"
          and board.ladder.level >= 1,
          "canary mismatch trips the breaker degradation ladder")
    cm = bundles("canary_mismatch")
    check(len(cm) == 1 and "wrong tokens" in cm[0]["cause"],
          "exactly one canary_mismatch bundle (wrong-but-finite captured)")

    # 6. Fleet failover: 2 replicas, kill r1 mid-sweep — zero lost, migrated
    # survivors token-identical to the single-engine baseline, r0 serving
    # throughout, r1 rejoining via its canary probe.
    from fairness_llm_tpu.config import FleetConfig, IntegrityConfig  # noqa: E402
    from fairness_llm_tpu.serving import ReplicaSet  # noqa: E402

    fleet_inj = ScriptedFaultInjector(replica_crashes={"r1": 3})
    fleet = ReplicaSet(
        engine, SERVING, settings=GREEDY,
        fleet=FleetConfig(replicas=2, fence_cooldown_s=0.05),
        resilience=RESILIENCE, fault_injector=fleet_inj,
        integrity=IntegrityConfig(canary_max_tokens=8),
    )
    fleet_reqs = [Request(prompt=p, id=f"fleet_{rid}", settings=GREEDY)
                  for rid, p in PROMPTS.items()]
    fleet_results = {r.id: r for r in fleet.serve(fleet_reqs)}
    check(fleet_inj.replica_faults_fired == [("r1", "replica_crash")],
          "replica r1 crash fired once mid-sweep")
    check(set(fleet_results) == {f"fleet_{rid}" for rid in PROMPTS},
          "fleet: every request got a terminal Result (zero lost)")
    fleet_parity = True
    for rid, prompt in PROMPTS.items():
        res = fleet_results[f"fleet_{rid}"]
        if not res.ok:
            fleet_parity = False
            print(f"  fleet loss: {rid}: {res.finish_reason} ({res.error})")
            continue
        got, ref = np.asarray(res.tokens), baseline[rid]
        n = len(got)
        if n == 0 or not np.array_equal(got, ref[:n]) \
                or not np.all(ref[n:] == engine.tokenizer.pad_id):
            fleet_parity = False
            print(f"  fleet parity break: {rid}: {list(got)} vs {list(ref)}")
    check(fleet_parity,
          "fleet: ALL requests ok, token-identical to the greedy baseline")
    r0, r1 = fleet.replicas
    reg = T.get_registry()
    r0_completed = reg.read_value("serving_completed_total",
                                  component="serving", replica="r0")
    check(r0.fences == 0 and r0_completed > 0,
          f"healthy replica r0 never fenced, served {r0_completed:g} "
          "request(s) throughout")
    check(r1.fences == 1, "crashed replica r1 fenced exactly once")
    migrated = reg.read_value("fleet_migrated_requests_total",
                              component="fleet")
    recovered = reg.read_value("fleet_migrated_recovered_total",
                               component="fleet")
    check(migrated > 0 and migrated == recovered,
          f"fleet: migrated ({migrated:g}) == recovered ({recovered:g})")
    check(fleet.await_recovery(timeout_s=60.0)
          and reg.read_value("fleet_healthy_replicas", component="fleet") == 2,
          "crashed replica rejoined via canary probe; fleet whole again")
    check(fleet.last_failover_s is not None,
          f"failover recovery measured ({fleet.last_failover_s and round(fleet.last_failover_s, 4)}s "
          "fence -> first migrated token)")
    fb = bundles("fence")
    check(len(fb) == 1 and fb[0].get("replica") == "r1"
          and "replica_crash" in fb[0]["cause"],
          "exactly one fence bundle for r1 naming replica_crash")
    if fb:
        # The rendered postmortem: the causal chain must read from the
        # fence back through the decisions that drove it.
        report = T.render_incident_report(fb[0]["path"])
        chain = next((ln for ln in report.splitlines()
                      if ln.strip().startswith("fence(")), "")
        print(f"  incident-report chain: {chain.strip()}")
        check("fence(r1)" in chain,
              "incident-report renders the fence causal chain")

    # 7. Overload brownout (ISSUE 8): offer ~3x the queue's capacity with
    # mixed QoS classes. The shed controller must walk the brownout ladder
    # (batch sheds with explicit retry-after Results), interactive traffic
    # must keep flowing inside its TTFT SLO, no accepted request may be
    # lost, and the controller must de-escalate to level 0 after the flood.
    from fairness_llm_tpu.config import OverloadConfig  # noqa: E402
    from fairness_llm_tpu.telemetry.slo import (  # noqa: E402
        SLOTargets,
        set_slo_targets,
    )

    # Harness-appropriate SLO targets: a tiny CPU model meets 60 s TTFT
    # trivially, so the drill's escalation signal is the deterministic one
    # (queue depth), not compile-time TTFT outliers.
    set_slo_targets(SLOTargets(ttft_p95_s=60.0, e2e_p99_s=120.0))
    ov = OverloadConfig(
        enabled=True, queue_frac_threshold=0.75, queue_window_s=0.5,
        healthy_window_s=0.05, eval_interval_s=0.0, batch_token_cap=4,
        retry_after_s=0.25,
    )
    ov_serving = ServingConfig(enabled=True, num_slots=2, queue_capacity=12,
                               max_prompt_len=192, max_new_tokens=32,
                               decode_chunk=4)
    # The drill's earlier sections leave UNLABELED serving gauges behind
    # (notably a pegged slo_burn_rate from the fault storm); a distinct
    # replica label gives this section's shed controller its own burn
    # signal instead of a stale one — the 1-vCPU flake where warmup
    # escalated off section-5's burn gauge.
    ov_sched = ContinuousScheduler(engine, ov_serving, settings=GREEDY,
                                   overload=ov, replica="ovdrill")

    # Prime this scheduler's OWN prefill/cadence histograms (the deadline
    # estimator reads its replica-labeled p50s and never sheds while
    # telemetry is cold — two served requests warm it deterministically).
    prime = [Request(prompt=PROMPTS["ok0"], id=f"ov_prime_{i}",
                     settings=GREEDY) for i in range(2)]
    prime_ok = all(r.ok for r in ov_sched.serve(prime))
    check(prime_ok, "overload scheduler primed its replica-labeled "
                    "prefill/cadence telemetry")

    # 7a. Deadline-feasibility admission: with six requests stacked ahead
    # on two slots, a 1 ms deadline is provably unmeetable — the gate must
    # shed it AT SUBMIT (no prefill burned, no expiry later), using the
    # prefill/cadence telemetry the priming pass populated.
    warm = [Request(prompt=p, id=f"ov_warm_{i}", settings=GREEDY)
            for i, p in enumerate(list(PROMPTS.values())[:6])]
    for r in warm:
        assert ov_sched.submit(r)
    doomed = Request(prompt=PROMPTS["ok0"], id="ov_doomed", settings=GREEDY,
                     deadline_s=0.001)
    accepted = ov_sched.submit(doomed)
    doomed_res = ov_sched.take_result("ov_doomed")
    check(not accepted and doomed_res is not None
          and doomed_res.finish_reason == "shed"
          and bool(doomed_res.retry_after_s)
          and "unmeetable" in (doomed_res.error or ""),
          "provably-doomed deadline shed at admission with retry-after "
          f"({doomed_res and doomed_res.error})")
    ov_sched.drain()
    warm_ok = all((ov_sched.take_result(r.id) or Result(id=r.id, ok=False)).ok
                  for r in warm)
    check(warm_ok and ov_sched.shed_controller.level == 0,
          "under-capacity warmup served clean at overload level 0")

    # 7b. The flood: 30 batch + 6 interactive (3x the 12-deep queue), batch
    # first — the starvation scenario.
    base_prompts = list(PROMPTS.values())
    flood = [Request(prompt=base_prompts[i % len(base_prompts)],
                     id=f"ov_batch_{i:03d}", settings=GREEDY, qos="batch")
             for i in range(30)]
    flood += [Request(prompt=base_prompts[i % len(base_prompts)],
                      id=f"ov_int_{i}", settings=GREEDY, qos="interactive")
              for i in range(6)]
    flood_results = {r.id: r for r in ov_sched.serve(flood)}
    check(set(flood_results) == {r.id for r in flood},
          "overload flood: every request got a terminal Result")
    interactive = [flood_results[f"ov_int_{i}"] for i in range(6)]
    check(all(r.ok for r in interactive),
          "all interactive requests served through the flood")
    ttfts = sorted(r.ttft_s for r in interactive if r.ttft_s is not None)
    ttft_p95 = ttfts[min(len(ttfts) - 1, int(0.95 * len(ttfts)))] \
        if ttfts else None
    check(ttft_p95 is not None and ttft_p95 <= 60.0,
          f"interactive TTFT p95 ({ttft_p95 and round(ttft_p95, 3)}s) holds "
          "its SLO during the flood")
    batch_res = [flood_results[f"ov_batch_{i:03d}"] for i in range(30)]
    shed = [r for r in batch_res if r.finish_reason == "shed"]
    served_batch = [r for r in batch_res if r.finish_reason != "shed"]
    check(bool(shed) and all(r.retry_after_s for r in shed),
          f"{len(shed)} batch request(s) shed with explicit retry-after")
    check(all(r.ok for r in served_batch),
          f"zero accepted-then-lost: all {len(served_batch)} admitted batch "
          "requests terminal ok")
    parity_ov = True
    for r in interactive + served_batch:
        prompt = next(q.prompt for q in flood if q.id == r.id)
        ref = next(baseline[rid] for rid, p in PROMPTS.items() if p == prompt)
        n = len(r.tokens)
        if n == 0 or not np.array_equal(np.asarray(r.tokens), ref[:n]):
            parity_ov = False
            print(f"  overload parity break: {r.id}")
    check(parity_ov, "admitted requests token-for-token with baseline "
                     "across classes and shed/restore cycles")
    reg = T.get_registry()
    shed_batch = reg.read_value("shed_total", component="serving",
                                replica="ovdrill",
                                **{"class": "batch", "reason": "overload"})
    shed_doomed = reg.read_value("shed_total", component="serving",
                                 replica="ovdrill",
                                 **{"class": "interactive",
                                    "reason": "deadline_infeasible"})
    check(shed_batch > 0 and shed_doomed > 0,
          f"shed_total counters nonzero (overload={shed_batch:g}, "
          f"deadline_infeasible={shed_doomed:g})")
    import time as _time
    ctl = ov_sched.shed_controller
    # Derived-time de-escalation (no sleeps, no wall deadline): the first
    # evaluate sees a depth window aged past queue_window_s (every flood
    # sample pruned -> frac 0), then each further evaluate advances the
    # clock one healthy_window_s past the per-rung hysteresis restart —
    # exactly one rung down per step, however slow the host is.
    t = _time.monotonic() + ov.queue_window_s + 0.01
    for _ in range(16):
        if ctl.evaluate(now=t) == 0:
            break
        t += ov.healthy_window_s + 0.01
    check(ctl.level == 0 and reg.read_value(
              "overload_level", component="serving",
              replica="ovdrill") == 0,
          "shed controller de-escalated to level 0 after the flood")

    # 8. Fairness observability (ISSUE 9): the serving-neutrality audit and
    # counterfactual pair watch. Pair probes are byte-identical prompts
    # tagged with different groups — the serving-layer counterfactual: any
    # output or delivery difference between members is serving treatment,
    # not model bias. Fault-free first (must be silent), then faults
    # targeted at ONE group (must alert, with the pairs attributed).
    from fairness_llm_tpu.telemetry.fairness import get_fairness_monitor

    fair_prompts = [PROMPTS["ok0"], PROMPTS["flaky"], PROMPTS["pfault"],
                    PROMPTS["hangme"]]

    def fairness_requests(tag):
        reqs = []
        for i, p in enumerate(fair_prompts):
            for g in ("g_ctrl", "g_tgt"):
                reqs.append(Request(
                    prompt=p, id=f"fair_{tag}_{g}_{i}", settings=GREEDY,
                    group=g, attribute="drill", pair_id=f"fair_{tag}_p{i}",
                ))
        return reqs

    mon = get_fairness_monitor()
    mon.begin_study()
    fair_sched = ContinuousScheduler(engine, SERVING, settings=GREEDY)
    ctrl = {r.id: r for r in fair_sched.serve(fairness_requests("ctrl"))}
    reg = T.get_registry()
    alerts_before = reg.read_value(
        "fairness_alerts_total", component="fairness", attribute="drill",
        signal="impaired_rate",
    )
    check(all(r.ok for r in ctrl.values())
          and mon.pairs_joined == len(fair_prompts)
          and mon.pairs_divergent == 0 and alerts_before == 0,
          f"fault-free neutrality control silent ({mon.pairs_joined} pairs "
          "joined, zero divergence, no alert)")

    # Fresh study for the biased half: sharing the control run's stats
    # would dilute the end-state disparity to exactly the alert threshold
    # (2 impaired over 8 = 0.25), making the alert depend on terminal
    # ordering; reset makes it 2/4 = 0.5, deterministic.
    mon.begin_study()
    biased_inj = ScriptedFaultInjector(
        faults={(f"fair_biased_g_tgt_{i}", "decode"): 2 for i in (0, 1)},
    )
    biased_sched = ContinuousScheduler(engine, SERVING, settings=GREEDY,
                                       fault_injector=biased_inj)
    biased = {r.id: r for r in biased_sched.serve(
        fairness_requests("biased"))}
    alerts_after = reg.read_value(
        "fairness_alerts_total", component="fairness", attribute="drill",
        signal="impaired_rate",
    )
    targeted_failed = [rid for rid, r in biased.items()
                       if "g_tgt" in rid and not r.ok]
    check(len(targeted_failed) == 2,
          "group-targeted faults failed exactly the targeted requests "
          f"({targeted_failed})")
    check(alerts_after >= 1,
          f"neutrality audit raised fairness_alerts_total "
          f"({alerts_after:g}) on group-targeted faults")
    divergent = [d for d in mon.divergent
                 if d["pair_id"].startswith("fair_biased")]
    attributed = [
        d for d in divergent
        if any("requeued" in e for m in d["members"].values()
               for e in (m.get("events") or []))
    ]
    check(len(divergent) >= 2 and len(attributed) >= 2,
          f"{len(divergent)} divergent pair(s) attributed to the injected "
          f"faults' requeues ({len(attributed)} with requeue events)")
    disparity = reg.read_value("fairness_disparity", component="fairness",
                               attribute="drill", signal="impaired_rate")
    check(disparity >= 0.25,
          f"impaired-rate disparity gauge reflects the bias "
          f"({disparity:g})")
    fa = bundles("fairness_alert", scope="drill")
    pd = bundles("pair_divergence", scope="drill")
    check(len(fa) == 1,
          "exactly one fairness_alert bundle for the biased-fault family")
    check(len(pd) == 1,
          "exactly one pair_divergence bundle (second divergent pair "
          "deduped into it)")
    if a.telemetry_dir:
        # The rendered fairness report rides the telemetry artifact — the
        # failure-evidence upload includes the attribution table.
        from fairness_llm_tpu.telemetry import render_fairness_report

        with open(os.path.join(a.telemetry_dir, "fairness_report.txt"),
                  "w", encoding="utf-8") as f:
            f.write(render_fairness_report(
                T.snapshot(T.get_registry()),
                events=[{"kind": "fairness_pair_divergent", **d}
                        for d in mon.divergent],
            ) + "\n")

    # 9. Paged KV prefix reuse under faults (ISSUE 10): the defining
    # workload shape — near-duplicate prompts sharing a long prefix —
    # through a paged scheduler with a scarce arena (~1 slot's worth + 2
    # blocks, so block recycling is constant), with a mid-sweep decode
    # fault hitting a request whose prefix blocks are SHARED with its
    # live twin. The fault releases the victim's slot (derefs the shared
    # chain) while the twin keeps decoding through the same blocks; the
    # requeue re-admits through the radix index. Parity against the
    # static engine is the no-stale-block-reads proof.
    import dataclasses as _dc

    paged_cfg = _dc.replace(SERVING, paged_kv=True, kv_block_size=16)
    probe_sched = ContinuousScheduler(engine, paged_cfg, settings=GREEDY)
    scarce_blocks = probe_sched.pool.paged.blocks_per_slot + 2
    del probe_sched  # existed only to read blocks_per_slot; free its arena
    paged_cfg = _dc.replace(paged_cfg, kv_blocks=scarce_blocks)
    stem = ("recommend five movies for a user who enjoyed Alien, Heat, "
            "Fargo, Tron and likes thrillers; profile ")
    fam = [stem + t for t in ("male 18-24", "female 18-24", "male 25-34",
                              "female 25-34", "male 35-44", "female 35-44")]
    paged_baseline = {
        f"paged{i}": np.asarray(engine.generate([p], GREEDY).tokens[0])
        for i, p in enumerate(fam)
    }
    # paged1's prefix is shared with paged0 (served just before it) — the
    # fault lands while those blocks are cached/refcounted.
    paged_inj = ScriptedFaultInjector(faults={("paged1", "decode"): 1})
    paged_sched = ContinuousScheduler(engine, paged_cfg, settings=GREEDY,
                                      fault_injector=paged_inj)
    paged_res = {r.id: r for r in paged_sched.serve(
        [Request(prompt=p, id=f"paged{i}", settings=GREEDY)
         for i, p in enumerate(fam)]
    )}
    check(len(paged_res) == len(fam)
          and all(r.ok for r in paged_res.values()),
          "paged chaos: zero lost under mid-sweep fault + scarce arena")
    paged_parity = all(
        np.array_equal(np.asarray(r.tokens),
                       paged_baseline[rid][:len(r.tokens)])
        and np.all(paged_baseline[rid][len(r.tokens):]
                   == engine.tokenizer.pad_id)
        for rid, r in paged_res.items()
    )
    check(paged_parity,
          "paged chaos: survivors token-identical (no stale-block reads)")
    check(paged_res["paged1"].retries == 1,
          "paged chaos: shared-prefix victim requeued exactly once")
    pkv = paged_sched.pool.paged
    check(pkv._hit_tokens > 0 and pkv.hit_ratio > 0.5,
          f"paged chaos: radix cache hit through the churn "
          f"(ratio {pkv.hit_ratio:.2f})")
    tree_blocks = 0
    stack = [pkv.index.root]
    while stack:
        node = stack.pop()
        for child in node.children.values():
            tree_blocks += 1
            stack.append(child)
    check(pkv.free_blocks + tree_blocks == pkv.num_blocks
          and not pkv._private,
          "paged chaos: block accounting whole at drain "
          f"(free {pkv.free_blocks} + cached {tree_blocks} "
          f"== {pkv.num_blocks})")

    # 10. FUSED multi-step dispatch under faults (ISSUE 14,
    # runtime/stepbuilder.py): the same containment contract with the
    # dispatch boundary MOVED — --fuse-steps 4 folds four decode chunks
    # into one compiled call, and an injected NaN lands INSIDE that fused
    # window. The numerics-guard flag rides the fused carry, so the whole
    # dispatch discards at its boundary as one NumericsFault, every rider
    # requeues once, and survivors decode token-identical: fusion may
    # widen the blast radius per fault (k chunks of work), never the
    # outcome.
    reg = T.get_registry()
    nf_before = reg.read_value("numerics_faults_total",
                               component="serving", stage="decode")
    fused_cfg = _dc.replace(SERVING, fuse_steps=4)
    fused_fam = list(PROMPTS.values())[:4]
    fused_baseline = {
        f"fused{i}": np.asarray(engine.generate([p], GREEDY).tokens[0])
        for i, p in enumerate(fused_fam)
    }
    fused_inj = ScriptedFaultInjector(
        {}, corruptions={("fused1", "decode"): 1})
    fused_sched = ContinuousScheduler(engine, fused_cfg, settings=GREEDY,
                                      fault_injector=fused_inj,
                                      resilience=RESILIENCE)
    fused_res = {r.id: r for r in fused_sched.serve(
        [Request(prompt=p, id=f"fused{i}", settings=GREEDY)
         for i, p in enumerate(fused_fam)]
    )}
    check(len(fused_res) == len(fused_fam)
          and all(r.ok for r in fused_res.values()),
          "fused chaos: zero lost under NaN inside a fused window")
    fused_parity = all(
        np.array_equal(np.asarray(r.tokens),
                       fused_baseline[rid][:len(r.tokens)])
        and np.all(fused_baseline[rid][len(r.tokens):]
                   == engine.tokenizer.pad_id)
        for rid, r in fused_res.items()
    )
    check(fused_parity,
          "fused chaos: survivors token-identical across the moved "
          "dispatch boundary")
    check(fused_res["fused1"].retries == 1,
          "fused chaos: poisoned rider requeued exactly once")
    nf_after = reg.read_value("numerics_faults_total",
                              component="serving", stage="decode")
    check(nf_after > nf_before,
          "fused chaos: the fused window's NaN classified as a "
          f"NumericsFault ({nf_before:g} -> {nf_after:g})")
    from fairness_llm_tpu.runtime.stepbuilder import compile_key as _ck

    check(_ck("serve_step", chunk=SERVING.decode_chunk, guard=True, fuse=4)
          in fused_sched._compiled,
          "fused chaos: the dispatch compiled under the fused key "
          "(chunk, guard, fuse)")

    # 11. Memory-pressure drill (ISSUE 18, telemetry/memory.py): the same
    # scarce arena as section 9 under its OWN incident scope
    # (replica="memdrill" — section 9's exhaustion already owns the
    # default "serving" dedup key) with an injected analytic HBM budget
    # (CPU reports no memory_stats; reconciliation stays "indicative").
    # Admission demand beyond the arena must defer exactly as before —
    # the allocator stays the hard gate — while the ledger (a) fires
    # EXACTLY ONE deduplicated memory_pressure bundle naming the
    # deferring requests and (b) drops the recoverable
    # memory_pressure_active gauge back to 0 once decode frees blocks
    # and admission succeeds.
    from fairness_llm_tpu.telemetry.memory import get_memory_ledger

    mem = get_memory_ledger()
    mem_sched = ContinuousScheduler(engine, paged_cfg, settings=GREEDY,
                                    replica="memdrill")
    mem.set_analytic_limit(mem.total_bytes() + (64 << 20))
    mem_res = {r.id: r for r in mem_sched.serve(
        [Request(prompt=p, id=f"mem{i}", settings=GREEDY)
         for i, p in enumerate(fam)]
    )}
    check(len(mem_res) == len(fam) and all(r.ok for r in mem_res.values()),
          "memory drill: zero lost under scarce-arena pressure")
    mem_bundles = bundles("memory_pressure", scope="memdrill")
    check(len(mem_bundles) == 1,
          "memory drill: exactly one deduplicated memory_pressure bundle "
          f"({len(mem_bundles)} found)")
    named = ((mem_bundles[0].get("context") or {}).get("request_ids")
             if mem_bundles else None) or []
    check(bool(named) and all(str(r).startswith("mem") for r in named),
          f"memory drill: bundle names the deferring requests ({named})")
    check(reg.read_value("memory_pressure_active", default=-1.0,
                         component="memory", replica="memdrill") == 0.0,
          "memory drill: memory_pressure_active recovered to 0 at drain")
    check(reg.read_value("hbm_headroom_bytes", component="memory",
                         reconciliation="indicative") > 0,
          "memory drill: headroom gauge published against the analytic "
          "budget (indicative)")
    mem.set_analytic_limit(None)

    snap = T.snapshot(T.get_registry())
    # Unlabeled entries only: the fleet section's per-replica boards write
    # breaker_transitions_total{replica=...} rows for the SAME (stage, to)
    # keys, and letting them shadow the single-engine board's entries
    # would validate r1's rejoin cycle in place of the documented
    # sections-1-5 cycle.
    trans = {
        (c["labels"].get("stage"), c["labels"].get("to")): c["value"]
        for c in snap["counters"]
        if c["name"] == "breaker_transitions_total"
        and "replica" not in c["labels"]
    }
    for to in ("open", "half_open", "closed"):
        check(trans.get(("decode", to), 0) >= 1,
              f"breaker_state transition to={to} in snapshot")
    hangs = [c for c in snap["counters"]
             if c["name"] == "watchdog_hangs_total" and c["value"] > 0]
    check(bool(hangs), "watchdog_hangs_total > 0 in snapshot")
    pre = [c for c in snap["counters"]
           if c["name"] == "serving_preempted_total" and c["value"] > 0]
    check(bool(pre), "serving_preempted_total > 0 in snapshot")
    for name in ("numerics_faults_total", "manifest_failures_total",
                 "canary_runs_total", "canary_mismatch_total"):
        hits = [c for c in snap["counters"]
                if c["name"] == name and c["value"] > 0]
        check(bool(hits), f"{name} > 0 in snapshot")
    # Dedup proof (ISSUE 13): the drill's fault storm fired far more
    # triggers than bundles — the suppressed counter is the difference.
    suppressed = sum(c["value"] for c in snap["counters"]
                     if c["name"] == "incident_suppressed_total")
    triggers = sum(c["value"] for c in snap["counters"]
                   if c["name"] == "incident_triggers_total")
    n_bundles = len(T.list_bundles(inc_dir))
    check(suppressed > 0 and triggers == suppressed + n_bundles,
          f"incident dedup: {triggers:g} trigger(s) -> {n_bundles} "
          f"bundle(s) + {suppressed:g} suppressed")
    check(sum(c["value"] for c in snap["counters"]
              if c["name"] == "decisions_total") > 0,
          "decision audit trail recorded (decisions_total > 0)")

    if a.telemetry_dir:
        path = T.write_snapshot(T.get_registry(), a.telemetry_dir)
        bad = T.validate_snapshot(T.load_snapshot(path))
        check(not bad, f"snapshot schema valid ({path})")
        if sink is not None:
            T.install_event_sink(None)
            sink.close()
    trace_path = a.trace_out or (os.path.join(a.telemetry_dir, "trace.json")
                                 if a.telemetry_dir else None)
    if trace_path:
        T.get_timeline().export(trace_path)
        tbad = T.validate_chrome_trace(
            T.get_timeline().to_chrome_trace()
        )
        check(not tbad, f"device-step timeline valid ({trace_path})")

    print(f"\nchaos drill: {'PASS' if not problems else 'FAIL'} "
          f"({len(problems)} problem(s))")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())

"""Chaos drill: scripted faults + an injected hang + a real mid-run SIGTERM,
then resume — the end-to-end proof behind docs/RESILIENCE.md.

What it does, in one process, deterministically:

1. builds a tiny CPU engine and records an UNINTERRUPTED baseline (the
   greedy tokens every request should decode);
2. re-serves the same workload through a resilience-armed scheduler with a
   scripted fault mix (one transient decode fault, one permanent one, one
   prefill fault), one injected hang (watchdog-classified, no real sleep),
   and a journal — and raises a REAL ``SIGTERM`` at itself the moment the
   late cohort reaches decode, so the ``GracefulDrain`` handler drains the
   run mid-flight;
3. resumes the journal's unfinished requests (``resume_serving``) in a
   fresh scheduler;
4. validates the ISSUE-4 acceptance: every request terminal (zero lost),
   survivors token-for-token equal to the baseline, the decode breaker's
   closed -> open -> half-open -> closed cycle present in the telemetry
   snapshot, the hang counted, and the journal empty.

Usage (CI runs exactly this):
    JAX_PLATFORMS=cpu python tools/chaos_drill.py --telemetry-dir chaos-tel
"""

from __future__ import annotations

import argparse
import os
import signal
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from fairness_llm_tpu.config import ModelSettings, ResilienceConfig, ServingConfig  # noqa: E402
from fairness_llm_tpu.models.configs import get_model_config  # noqa: E402
from fairness_llm_tpu.resilience import (  # noqa: E402
    GracefulDrain,
    ServingJournal,
    resume_serving,
)
from fairness_llm_tpu.runtime.engine import DecodeEngine  # noqa: E402
from fairness_llm_tpu.serving import ContinuousScheduler, Request  # noqa: E402
from fairness_llm_tpu.utils.failures import ScriptedFaultInjector  # noqa: E402

GREEDY = ModelSettings(temperature=0.0, max_tokens=8)
SERVING = ServingConfig(enabled=True, num_slots=2, queue_capacity=64,
                        max_prompt_len=192, max_new_tokens=32, decode_chunk=4)
# Generous watchdog budget: only the injector's SIMULATED 3600 s stalls may
# classify as hangs — a real chunk on a loaded CI runner (first one includes
# XLA compilation) must never trip it, or the drill turns flaky.
RESILIENCE = ResilienceConfig(enabled=True, max_step_seconds=120.0,
                              breaker_threshold=1, breaker_cooldown_s=0.02,
                              drain_grace_s=30.0)

PROMPTS = {
    "ok0": "the quick brown fox",
    "flaky": "hello there friend",      # one transient decode fault
    "doomed": "abc abc abc abc abc",    # permanent decode fault -> failed
    "pfault": "one two three one two",  # one prefill fault
    "hangme": "recommend ten films please",  # one injected hang
    "late0": "zz zz zz",                # reaching decode triggers SIGTERM
    "late1": "a long prompt that shifts padding and lands in a bucket",
}


class SigtermOnSight(ScriptedFaultInjector):
    """Raises a real SIGTERM at our own process the first time the late
    cohort reaches decode — the GracefulDrain handler (installed around the
    serve) turns it into a drain request the scheduler honors at its next
    loop iteration. Deterministic 'preemption notice mid-run'."""

    def __init__(self, faults, hangs):
        super().__init__(faults, hangs=hangs)
        self._fired_sigterm = False

    def maybe_fail(self, request_id, stage):
        if request_id == "late0" and stage == "decode" and not self._fired_sigterm:
            self._fired_sigterm = True
            signal.raise_signal(signal.SIGTERM)
        super().maybe_fail(request_id, stage)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--telemetry-dir", default=None,
                    help="write events.jsonl + the validated snapshot here")
    ap.add_argument("--journal-dir", default=None,
                    help="serving journal dir (default: a temp dir)")
    a = ap.parse_args()

    from fairness_llm_tpu import telemetry as T

    sink = T.configure(a.telemetry_dir) if a.telemetry_dir else None
    journal_dir = a.journal_dir or tempfile.mkdtemp(prefix="chaos-journal-")

    problems = []

    def check(ok: bool, what: str) -> None:
        print(("PASS" if ok else "FAIL") + f"  {what}")
        if not ok:
            problems.append(what)

    engine = DecodeEngine(get_model_config("tiny-test"), seed=0)

    # 1. Uninterrupted baseline: the tokens every survivor must reproduce.
    baseline = {}
    for rid, prompt in PROMPTS.items():
        out = engine.generate([prompt], GREEDY)
        baseline[rid] = np.asarray(out.tokens[0])

    # 2. The chaos run.
    journal = ServingJournal(journal_dir)
    inj = SigtermOnSight(
        faults={("flaky", "decode"): 1, ("doomed", "decode"): 2,
                ("pfault", "prefill"): 1},
        hangs={("hangme", "decode"): 1},
    )
    sched = ContinuousScheduler(engine, SERVING, settings=GREEDY,
                                fault_injector=inj, resilience=RESILIENCE,
                                journal=journal)
    reqs = [Request(prompt=p, id=rid, settings=GREEDY)
            for rid, p in PROMPTS.items()]
    with GracefulDrain():
        results = {r.id: r for r in sched.serve(reqs)}
    preempted = sorted(rid for rid, r in results.items()
                       if r.finish_reason == "preempted")
    print(f"chaos run: { {rid: r.finish_reason for rid, r in results.items()} }")
    check(set(results) == set(PROMPTS), "every request got a phase-1 Result")
    check(bool(preempted), "SIGTERM drained a late cohort to the journal")
    check(inj.hangs_fired == [("hangme", "decode")], "the hang fired once")
    check(sorted(r["id"] for r in journal.unfinished()) == preempted,
          "journal unfinished == preempted set")

    # 3. Resume.
    resumed = resume_serving(engine, journal, serving=SERVING,
                             resilience=RESILIENCE)
    check(sorted(resumed) == preempted, "resume served exactly the journal")
    check(journal.unfinished() == [], "journal empty after resume")

    # 4. Acceptance: zero lost + survivor parity + breaker cycle visible.
    final = {**results, **resumed}
    lost = set(PROMPTS) - set(final)
    check(not lost, f"zero lost requests (missing: {sorted(lost) or 'none'})")
    check(not final["doomed"].ok and final["doomed"].finish_reason == "failed",
          "permanent fault terminated failed (requeue-once, not forever)")
    parity_ok, survivors = True, 0
    for rid, res in final.items():
        if not res.ok:
            continue
        survivors += 1
        n = len(res.tokens)
        ref = baseline[rid]
        if n == 0 or not np.array_equal(np.asarray(res.tokens), ref[:n]) \
                or not np.all(ref[n:] == engine.tokenizer.pad_id):
            parity_ok = False
            print(f"  parity break: {rid}: {list(res.tokens)} vs {list(ref)}")
    check(parity_ok and survivors >= len(PROMPTS) - 2,
          f"{survivors} survivors all token-for-token with baseline")

    snap = T.snapshot(T.get_registry())
    trans = {
        (c["labels"].get("stage"), c["labels"].get("to")): c["value"]
        for c in snap["counters"] if c["name"] == "breaker_transitions_total"
    }
    for to in ("open", "half_open", "closed"):
        check(trans.get(("decode", to), 0) >= 1,
              f"breaker_state transition to={to} in snapshot")
    hangs = [c for c in snap["counters"]
             if c["name"] == "watchdog_hangs_total" and c["value"] > 0]
    check(bool(hangs), "watchdog_hangs_total > 0 in snapshot")
    pre = [c for c in snap["counters"]
           if c["name"] == "serving_preempted_total" and c["value"] > 0]
    check(bool(pre), "serving_preempted_total > 0 in snapshot")

    if a.telemetry_dir:
        path = T.write_snapshot(T.get_registry(), a.telemetry_dir)
        bad = T.validate_snapshot(T.load_snapshot(path))
        check(not bad, f"snapshot schema valid ({path})")
        if sink is not None:
            T.install_event_sink(None)
            sink.close()

    print(f"\nchaos drill: {'PASS' if not problems else 'FAIL'} "
          f"({len(problems)} problem(s))")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())

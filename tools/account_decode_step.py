"""Component accounting for the decode step (VERDICT r3 item 6).

Traces the 45-profile sweep on the live chip, aggregates EVERY device op in
the capture, classifies ops into decode-step components, and prints a table
whose rows sum to the measured device time — so the "remaining gap to the
streaming ceiling is work the step must do" claim rests on a full
accounting, not one attention-only harness.

    python tools/account_decode_step.py [model] [out.json]
"""

from __future__ import annotations

import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Classification: the SHARED component taxonomy (telemetry/costmodel.py) —
# the same first-match-wins table the live jaxpr cost ledger publishes
# under, so an offline xplane capture and the live cost_ledger_bytes gauges
# bucket work identically. The patterns/order are the ones this tool owned
# through round 11 (regression-pinned in tests/test_costmodel.py).
from fairness_llm_tpu.telemetry.costmodel import (  # noqa: E402
    COMPONENT_TITLES,
    COMPONENTS,
    classify,
)


def run(model_name: str = "gpt2-small") -> dict:
    import jax

    from bench import MAX_NEW_TOKENS, build_sweep_prompts
    from fairness_llm_tpu.config import ModelSettings
    from fairness_llm_tpu.models.configs import get_model_config
    from fairness_llm_tpu.runtime.engine import DecodeEngine
    from fairness_llm_tpu.utils.profiling import summarize_trace

    prompts = build_sweep_prompts()
    settings = ModelSettings(
        temperature=0.7, top_k=0, top_p=1.0, max_tokens=MAX_NEW_TOKENS
    )
    eng = DecodeEngine(get_model_config(model_name), seed=0)
    out = eng.generate(prompts, settings, seed=0)  # warmup/compile

    trace_dir = tempfile.mkdtemp(prefix="decode_trace_")
    with jax.profiler.trace(trace_dir):
        out = eng.generate(prompts, settings, seed=1)
        jax.block_until_ready(out.tokens)

    summaries = summarize_trace(trace_dir, top_k=100000, device_filter="TPU")
    # one capture, one TPU plane expected on the single chip
    s = summaries[0]
    buckets: dict = {}
    for name, ms, cnt in s.top_ops:
        label = classify(name)
        b = buckets.setdefault(label, {"ms": 0.0, "events": 0, "top": []})
        b["ms"] += ms
        b["events"] += cnt
        b["top"].append((round(ms, 2), cnt, name[:90]))
    for b in buckets.values():
        b["top"] = sorted(b["top"], reverse=True)[:5]
        b["ms"] = round(b["ms"], 2)

    steps = MAX_NEW_TOKENS  # random weights never EOS: full trip count
    all_ops = sorted(
        ((round(ms, 3), cnt, name[:160]) for name, ms, cnt in s.top_ops),
        reverse=True,
    )[:150]
    table = sorted(buckets.items(), key=lambda kv: -kv[1]["ms"])
    result = {
        "model": model_name,
        "device_total_ms": round(s.total_ms, 1),
        "num_events": s.num_events,
        "decode_steps": steps,
        "decode_shape": out.stats,
        "top_ops": all_ops,
        "components": {
            label: {
                "ms": b["ms"],
                "ms_per_step": round(b["ms"] / steps, 4),
                "pct": round(100 * b["ms"] / s.total_ms, 1),
                "events": b["events"],
                "top_ops": b["top"],
            }
            for label, b in table
        },
    }
    return result


if __name__ == "__main__":
    model = sys.argv[1] if len(sys.argv) > 1 else "gpt2-small"
    res = run(model)
    if len(sys.argv) > 2:
        with open(sys.argv[2], "w") as f:
            json.dump(res, f, indent=1)
    comps = res.pop("components")
    print(json.dumps(res))
    for label, c in comps.items():
        title = COMPONENT_TITLES.get(label, label)
        print(f"{c['ms']:9.1f} ms ({c['pct']:4.1f}%)  x{c['events']:7d}  {title}")
        for ms, cnt, name in c["top_ops"][:3]:
            print(f"    {ms:8.2f} ms x{cnt:6d}  {name}")
